"""Continuous-batching scheduler for the paged-KV serving layer.

The static fused path (``InferenceEngine.generate``) runs whole-batch
lockstep: every row prefills together and decodes until the SLOWEST row
finishes — head-of-line blocking under mixed-length traffic. This
scheduler instead runs a fixed set of decode SLOTS against one
static-shape decode program and admits queued requests into slots the
moment they free: an arriving request is prefilled (its prompt's KV lands
in pool blocks) while the in-flight slots keep decoding, and a finishing
sequence returns its blocks to the pool for the next arrival. Occupancy —
not program shape — is what varies (DeepSpeed-Inference arXiv:2207.00032;
Orca/vLLM-style iteration-level scheduling on top of the paged pool).

Block allocation is ON-DEMAND (vLLM-style): admission allocates only the
PROMPT's blocks, and each slot's table grows at decode-chunk boundaries
just ahead of the KV it is about to write — pool capacity tracks live
tokens, not the admission-time worst case ``prompt + max_new_tokens``,
which is what lets a given pool admit MORE concurrent slots (the
unified ragged Pallas kernel then keeps the per-step KV traffic
proportional to the same live tokens;
ops/paged_attention_kernel.py). When the pool
cannot supply a mid-decode grow, the slot STALLS — excluded from decode
calls (its in-program writes are masked off), tables intact — and
resumes the step blocks free. If every active slot is stalled at once
(only possible with >= 2 slots sharing a too-small pool), the youngest
slot is PREEMPTED: its blocks recycle and its request requeues at the
queue head for a fresh admission, guaranteeing progress. Preemption
restarts that request's generation from its prompt (greedy output is
unchanged — same tokens recomputed; a sampled stream restarts
self-consistently from its seed). ``reserve_upfront=True`` restores the
old reserve-everything-at-admission policy (no growth, no stalls) for
A/B comparison. Note per-slot rng streams advance with decode program
steps, so a stall can shift WHERE a sampled stream lands relative to an
unstalled run; (prompt, seed) determinism at fixed pool pressure holds.

CHUNKED PREFILL / TOKEN-BUDGET SCHEDULING (serve.prefill_chunk_tokens,
docs/SERVING.md): with a chunk budget set, admission binds a slot but
feeds NO tokens; each step assigns pending prompts chunks of at most
``prefill_chunk_tokens`` new tokens (the per-step budget, fair-shared
across concurrently-prefilling slots in admission order) and packs
them plus every runnable decode slot into ONE
``executor.ragged_step`` call — the
unified ragged kernel serves the mixed batch in a single launch, so a
long prompt no longer stalls decoding slots for its whole prefill: the
worst gap it adds between two decode tokens is one chunk's model time.
The FINAL chunk's sampled token is the request's first output token
(mid-chunk samples advance nothing, including the slot's rng stream);
greedy output is byte-identical with chunking on, off, and vs
``generate()``. Chunk boundaries are ordinary step boundaries, so
every contract below — deadlines, cancellation, preemption, restores,
spills, tracing spans, the auditor — holds identically (the chaos
suite runs every scenario in both modes).

FAULT TOLERANCE (docs/SERVING.md): every submitted request resolves to
exactly ONE terminal :class:`Completion` whose ``status`` is one of
:data:`TERMINAL_STATUSES` — executor errors are isolated to the request
they belong to (a slot-attributed
:class:`~deepspeed_tpu.inference.faults.RequestFault` fails one request,
an unattributed exception fails the runnable set, and either way the
queue keeps draining instead of the whole ``serve()`` call raising),
``cancel(rid)`` / per-request deadlines / queue-wait timeouts are
enforced cooperatively at chunk boundaries, total-stall preemption is
bounded (``max_preemptions``) with preempt-age-aware victim rotation so
no request can starve or livelock, and EVERY exit path releases the
slot's blocks (deref-only for shared prefix-cache blocks). A cheap
host-side invariant auditor (:meth:`ContinuousBatchingScheduler.audit`)
cross-checks refcounts/tables/free lists/prefix index every
``audit_every`` chunks and fails fast with the full violation report.
The deterministic seeded :class:`~deepspeed_tpu.inference.faults.
FaultInjector` drives the chaos suite
(tests/unit/inference/test_chaos.py) and ``bench.py --serve --chaos``.

TIERED KV (inference/kv_tiering.py, docs/SERVING.md): with a
``host_tier``, device-LRU eviction stops being the end of a prefix's
life. The caching pool's eviction hook queues (content key, block id)
pairs and the scheduler flushes a device→host SPILL before any executor
call could rewrite the reclaimed frames; admission's prefix lookup then
walks device-then-host — a host hit claims fresh pool blocks and
dispatches an async host→device RESTORE (``begin_restore``) whose
transfer overlaps the decode chunk of the SAME step, and the slot sits
in a RESTORING state (admitted, blocks held, excluded from decode) until
the next step boundary finishes the restore and prefills only the
still-uncached tail. The tier is strictly opportunistic: it never blocks
allocation (spills/restores are bounded host-RAM copies with their own
byte-capped LRU), a cleanly failed restore DEGRADES that one request to
a cold prefill (not a FAILED terminal, co-scheduled streams
byte-identical — only a scatter that dies mid-flight on the donated
pools escalates to the unattributed-error blast radius), and greedy
outputs are exactly the untiered path's.

OBSERVABILITY (deepspeed_tpu/observability, docs/OBSERVABILITY.md):
with a ``tracer`` the scheduler emits per-request lifecycle spans at
its existing host-call boundaries — ``QUEUED`` (submit→admission),
``PREFILL``, per-chunk ``DECODE`` with slot/step attribution,
``RESTORING``, and exactly ONE terminal event per request whose status
matches the returned :class:`Completion` — plus instants for
preemption/stall/spill/restore-degrade, auditor failures and injected
chaos firings; with a ``metrics`` registry it maintains the serve
counters/gauges/histograms (``serve.ttft_s``, ``serve.tpot_s``,
``serve.queue_wait_s``, per-status completion counts, pool occupancy)
behind ``engine.serve_metrics()``. Both are strictly host-side (span
timestamps are ``time.monotonic()`` captured BETWEEN executor calls) —
the compiled programs carry zero observability ops, which dstlint's
jaxpr budgets pin.

The scheduler is pure host logic over an EXECUTOR protocol, so its
admission/recycling/backpressure/growth behavior is unit-tested with a
fake executor (tests/unit/inference/test_scheduler.py); the real
executor — compiled prefill/decode programs over the device block pool —
lives in ``inference/engine.py`` (``InferenceEngine.serve``). Executors
expose their decode chunk as an optional ``decode_chunk`` attribute
(default 1) — the growth horizon per decode call.

Executor protocol (duck-typed)::

    set_slot(slot: int, req: Request) -> None
        # bind per-slot sampling state (rng key, temperature, top_k,
        # top_p, eos) — isolation per slot is part of the contract
    prefill(slot: int, prompt: np.ndarray, block_row: np.ndarray) -> int
        # write the prompt's KV through the slot's block-table row,
        # return the first sampled token. With prefix caching the
        # scheduler passes a 4th positional arg ``start`` when (and only
        # when) a cached prefix was reused: KV for prompt[:start] is
        # already in the table's shared blocks, so the executor prefills
        # prompt[start:] at write position ``start`` (offset prefill)
    copy_blocks(pairs: List[Tuple[int, int]]) -> None
        # prefix-cache CoW: duplicate device KV of block src into dst for
        # each (src, dst) pair, across every layer/pool. Called before
        # the slot's first write; only required of executors driven with
        # prefix_cache=True
    decode(tokens, block_tables, seq_lens, active, steps_left,
           max_steps) -> np.ndarray
        # one program call over ALL slots: [num_slots] int32 last tokens
        # in, [num_slots, n] int32 sampled tokens out (n >= 1; chunked
        # executors may decode several steps per call — the scheduler
        # consumes per-slot tokens up to eos/budget and ignores the
        # rest). ``max_steps`` (int or None) caps n: the scheduler sets
        # it to the nearest slot completion while the queue holds work,
        # so chunking can never delay an admission past a free slot
    ragged_step(tokens, q_lens, block_tables, write_pos, emit,
                is_first) -> np.ndarray
        # chunked prefill only: ONE call over a MIXED ragged batch —
        # [num_slots, T_cap] right-padded per-slot token segments
        # (decode slots feed 1 token, prefill-chunk slots up to T_cap,
        # inactive slots 0 via q_lens), [num_slots] int32 sampled
        # tokens out. ``emit`` marks the slots whose sample the
        # scheduler consumes (decode slots + FINAL prefill chunks);
        # ``is_first`` marks the emitting subset whose sample is a
        # request's FIRST token, so the executor can reproduce the
        # split programs' rng-split convention exactly (seeded sampled
        # streams identical chunked on/off); non-emitting slots must
        # not advance their rng stream
    ragged_verify_step(tokens, q_lens, block_tables, write_pos, emit,
                       is_first, spec_lens) -> (nxt, verified, accepts)
        # speculative decoding only: ragged_step plus in-device draft
        # verification. A drafted decode slot feeds 1 + k tokens (its
        # last sampled token, then k = spec_lens[slot] prompt-lookup
        # draft tokens) as one ragged row. Returns [num_slots] sampled
        # tokens (consumed exactly as ragged_step's for undrafted
        # rows), [num_slots, T_cap] greedy-argmax continuations per
        # fed position, and [num_slots] accepted-prefix lengths
        # (0..k). For a drafted row the scheduler consumes
        # verified[slot, 0..accepts[slot]] — accepted draft tokens
        # plus the model's bonus token — and rolls back the rest; rng
        # discipline is ragged_step's (a drafted row advances its
        # stream once per step, like the 1-token row it replaces)
    spill_blocks(entries: List[Tuple[bytes, int]]) -> None
        # tiered KV only: copy the device KV frames of the listed block
        # ids into the host tier under their content keys. Called BEFORE
        # any executor call that could rewrite the reclaimed frames
    begin_restore(slot, entries: List[Tuple[bytes, int]]) -> handle|None
        # tiered KV only: start the async host→device transfer of the
        # tier frames for ``entries`` (fresh pool blocks the slot
        # already holds). Returns an opaque handle, or None when the
        # tier no longer has a key (the scheduler degrades to a cold
        # prefill). Must NOT touch the pools yet — the transfer overlaps
        # this step's decode chunk
    finish_restore(handle) -> bool
        # tiered KV only: land the staged frames in the pool blocks
        # (the jitted scatter). False = CLEAN failure, pools untouched
        # (the scheduler degrades that one request to a cold prefill);
        # raising means the scatter consumed the DONATED pools and died
        # — unknown pool state, unattributed-decode-error blast radius
"""

import dataclasses
import threading
import time
import zlib
from collections import deque
from typing import Any, Deque, Dict, Iterable, List, Optional, Set

import numpy as np

from deepspeed_tpu.inference.faults import FaultInjector, RequestFault
from deepspeed_tpu.inference.kv_pool import (
    BlockPool, PoolAuditError, PrefixCachingBlockPool, SlotBlockTables,
    block_content_keys, blocks_for,
)
from deepspeed_tpu.inference.speculative import propose_ngram_draft

# --- terminal request statuses ----------------------------------------------
#: the request ran its full course (eos or budget)
COMPLETED = "COMPLETED"
#: an executor error attributed to this request (others keep serving)
FAILED = "FAILED"
#: pre-admission validation refused the request (never held blocks)
REJECTED = "REJECTED"
#: client cancel() landed (cooperative, at a chunk boundary)
CANCELLED = "CANCELLED"
#: deadline_s / queue_timeout_s expired before completion
TIMED_OUT = "TIMED_OUT"
#: restart-from-prompt retries exhausted max_preemptions (no livelock)
PREEMPTED_LIMIT = "PREEMPTED_LIMIT"

TERMINAL_STATUSES = (COMPLETED, FAILED, REJECTED, CANCELLED, TIMED_OUT,
                     PREEMPTED_LIMIT)


@dataclasses.dataclass
class Request:
    """One generation request. ``arrival_time`` (absolute ``time.time()``
    seconds) gates admission for trace replay; None = eligible now.
    ``deadline_s`` is a wall-clock budget from submit (queued OR
    decoding — a request past it resolves ``TIMED_OUT`` at the next
    chunk boundary, partial tokens attached); ``queue_timeout_s`` bounds
    queue wait only (overrides the scheduler-level default)."""

    rid: Any
    prompt: np.ndarray                 # int32 [T], T >= 1
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: int = -1                   # < 0 disables EOS stopping
    seed: int = 0
    arrival_time: Optional[float] = None
    deadline_s: Optional[float] = None
    queue_timeout_s: Optional[float] = None
    # disaggregated serving (docs/SERVING.md): True marks a request a
    # prefill-role replica already prefilled and PUBLISHED into the
    # shared transfer tier — the decode-side scheduler expects its
    # admission lookup to cover the whole prompt, and counts/traces a
    # DISAGG_DEGRADE when it has to cold-prefill instead
    routed_prefill: bool = False
    # admission-control class (inference/admission.py): under overload
    # the controller sheds lowest-priority / longest-prompt first, so
    # higher values survive longer. 0 = default class.
    priority: int = 0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens must "
                             f"be >= 1")


@dataclasses.dataclass
class Completion:
    """A finished request: tokens + latency breakdown + terminal status.

    Every submitted request resolves to exactly one Completion — the
    fault-tolerance contract. ``status`` is one of
    :data:`TERMINAL_STATUSES`; non-``COMPLETED`` terminals carry the
    reason in ``error`` and whatever tokens were generated before the
    exit (``REJECTED``/queue ``TIMED_OUT``: none)."""

    rid: Any
    prompt: np.ndarray
    tokens: np.ndarray                 # generated tokens (incl. eos if hit)
    t_submit: float
    t_admitted: float
    t_first_token: float
    t_finish: float
    status: str = COMPLETED
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.status == COMPLETED

    @property
    def latency(self) -> float:
        return self.t_finish - self.t_submit

    @property
    def queue_delay(self) -> float:
        return self.t_admitted - self.t_submit


class _Slot:
    __slots__ = ("req", "seq_len", "remaining", "out", "t_admitted",
                 "t_first")

    def __init__(self):
        self.req: Optional[Request] = None
        self.seq_len = 0               # tokens whose KV is written
        self.remaining = 0             # generation budget left
        self.out: List[int] = []
        self.t_admitted = 0.0
        self.t_first = 0.0

    @property
    def free(self) -> bool:
        return self.req is None


class _Restore:
    """Restore-in-flight state for one admitted slot (tiered KV): the
    executor's transfer handle plus the two possible prefill starts —
    ``start`` when the staged frames land (prefill only the tail the
    tiers don't cover), ``dev_start`` when the restore fails (cold
    prefill of everything past the device-matched prefix; degrade, not
    FAILED)."""

    __slots__ = ("req", "handle", "entries", "start", "dev_start",
                 "t_admit", "t_mono", "attempt", "retry_at")

    def __init__(self, req, handle, entries, start, dev_start, t_admit,
                 t_mono=0.0, attempt=0, retry_at=0.0):
        self.req = req
        self.handle = handle
        self.entries = entries
        self.start = int(start)
        self.dev_start = int(dev_start)
        self.t_admit = t_admit
        self.t_mono = t_mono
        self.attempt = int(attempt)    # failed-restore retries so far
        self.retry_at = float(retry_at)  # backoff: not ready before this


class HandoffQueue:
    """Thread-safe prefill→decode handoff channel (disaggregated
    serving, docs/SERVING.md). A prefill-role replica ``put``s each
    request the moment its prompt KV is published into the shared
    transfer tier; the decode-role scheduler ``drain``s at every step
    boundary and submits the requests into its own queue — admission's
    tiered lookup then finds the published frames and the request lands
    already-prefilled through the ordinary restore machinery.

    ``expect(n)`` pre-registers handoffs still to come, so the decode
    scheduler's ``busy`` stays True (and its serve loop keeps stepping)
    while the prefill leg is still working; ``abandon(n)`` retracts
    expectations whose request will never arrive (the prefill leg
    surfaced a terminal itself). The publish ALWAYS happens before the
    ``put`` — the channel carries only requests whose frames are
    already lookup-able, so there is no publish/admit race to order."""

    def __init__(self, expected: int = 0):
        self._lock = threading.Lock()
        self._q: Deque[Request] = deque()
        self._expected = int(expected)

    def expect(self, n: int = 1) -> None:
        with self._lock:
            self._expected += int(n)

    def abandon(self, n: int = 1) -> None:
        with self._lock:
            self._expected = max(0, self._expected - int(n))

    def put(self, req: Request) -> None:
        with self._lock:
            self._q.append(req)
            self._expected = max(0, self._expected - 1)

    def drain(self) -> List[Request]:
        with self._lock:
            out = list(self._q)
            self._q.clear()
            return out

    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def done(self) -> bool:
        """Nothing queued and nothing further expected."""
        with self._lock:
            return self._expected <= 0 and not self._q

    def close(self) -> None:
        """Retract ALL outstanding expectations (prefill-role death:
        whatever was never handed off stops blocking the decode loop).
        Queued requests stay drainable."""
        with self._lock:
            self._expected = 0


class ContinuousBatchingScheduler:
    """FIFO request queue over ``num_slots`` decode slots + a block pool.

    One :meth:`step` = admit-what-fits, then one decode program call over
    all slots. Admission is strict FIFO: if the head request's blocks
    don't fit, the queue WAITS (backpressure) — nothing is dropped and
    nothing skips ahead, so completion order under load is predictable.
    """

    def __init__(self, executor, num_slots: int, pool: BlockPool,
                 table_width: int, reserve_upfront: bool = False,
                 record_occupancy: bool = False,
                 prefix_cache: bool = False,
                 max_preemptions: int = 8,
                 queue_timeout_s: Optional[float] = None,
                 audit_every: int = 64,
                 fault_injector: Optional[FaultInjector] = None,
                 host_tier=None, metrics=None, tracer=None, slo=None,
                 prefill_chunk_tokens: int = 0,
                 speculative: bool = False, draft_len: int = 8,
                 draft_ngram: int = 2,
                 handoff: Optional[HandoffQueue] = None,
                 publish_prefixes: bool = False,
                 admission=None, restore_retries: int = 0,
                 retry_backoff_s: float = 0.05,
                 readmit_failed: int = 0):
        self.executor = executor
        self.num_slots = int(num_slots)
        self.pool = pool
        # CHUNKED PREFILL / token-budget scheduling
        # (serve.prefill_chunk_tokens, docs/SERVING.md): > 0 switches
        # every executor call to the unified RAGGED STEP — admission
        # binds the slot but prefills NOTHING; each step assigns pending
        # prompts chunks of at most ``prefill_chunk_tokens`` NEW tokens
        # (the per-step budget, fair-shared across concurrently-
        # prefilling slots) and packs them plus all runnable decode
        # slots into one
        # ``executor.ragged_step`` call. Decode therefore emits a token
        # at every chunk boundary instead of stalling for a long
        # prompt's whole prefill, and chunk boundaries are ordinary
        # step boundaries — deadlines, cancellation, preemption,
        # restores, spills, tracing and the auditor keep their
        # semantics.
        self.chunk_tokens = int(prefill_chunk_tokens)
        if self.chunk_tokens < 0:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 0, got "
                f"{prefill_chunk_tokens}")
        if self.chunk_tokens and not hasattr(executor, "ragged_step"):
            raise ValueError(
                "prefill_chunk_tokens > 0 needs an executor with a "
                "ragged_step program (the unified mixed prefill+decode "
                f"call) — {type(executor).__name__} lacks it")
        # SPECULATIVE DECODING (serve.speculative="prompt_lookup",
        # docs/SERVING.md): each step the scheduler proposes up to
        # ``draft_len`` prompt-lookup draft tokens per runnable GREEDY
        # decode slot from the slot's host-side history (prompt + out —
        # no extra state to checkpoint: preemption's restart-from-prompt
        # discards drafts for free) and submits the slot as a T=1+k
        # ragged row through ``executor.ragged_verify_step``; the
        # longest draft prefix matching the model's greedy argmax is
        # consumed in one step, plus the model's own bonus token.
        # Drafts compete with chunked-prefill tokens for the same
        # per-step token budget; rejection trims the over-grown tail
        # blocks back to the pool (SlotBlockTables.trim). Routing: spec
        # forces the ragged path even when prefill_chunk_tokens == 0
        # (legacy prefill programs still do admission; decode rows go
        # ragged), and ``decode_chunk`` is ignored — one verify round
        # per scheduler step.
        self.spec = bool(speculative)
        self.draft_len = int(draft_len)
        self.draft_ngram = int(draft_ngram)
        if self.spec:
            if not hasattr(executor, "ragged_verify_step"):
                raise ValueError(
                    "speculative decoding needs an executor with a "
                    "ragged_verify_step program (the draft-verify "
                    f"ragged call) — {type(executor).__name__} lacks it")
            if self.draft_len < 1:
                raise ValueError(
                    f"draft_len must be >= 1, got {draft_len}")
            if self.draft_ngram < 1:
                raise ValueError(
                    f"draft_ngram must be >= 1, got {draft_ngram}")
        # speculative accounting (bench artifact / serve.spec collector):
        # drafted/accepted token totals, verify rounds that carried a
        # draft, and rows decoded without one (sampled slots, no match)
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_rounds = 0
        self.spec_plain_rows = 0
        # prefilling[s]: slot admitted, prompt KV partially written —
        # excluded from decode consumption until its final chunk lands;
        # _prefill_next[s] is the next prompt index to feed
        self.prefilling = np.zeros(num_slots, bool)
        self._prefill_next = np.zeros(num_slots, np.int64)
        # PREFIX CACHING: admission looks up the longest cached
        # block-aligned prefix of each prompt and claims only the
        # uncached tail (prefill starts at the first uncached token);
        # completion/preemption release references instead of freeing, so
        # full blocks stay reusable. Strictly opportunistic: the cache
        # never holds capacity admission needs (kv_pool.
        # PrefixCachingBlockPool makes cached blocks allocatable).
        self.prefix_cache = bool(prefix_cache)
        if self.prefix_cache and not isinstance(pool,
                                                PrefixCachingBlockPool):
            raise ValueError(
                "prefix_cache=True needs a PrefixCachingBlockPool (got "
                f"{type(pool).__name__}) — plain pools have no content "
                "index or refcounts")
        # hit accounting for the bench artifact / tests: blocks looked
        # up vs matched, prompt tokens total vs served from cache
        self.cache_lookup_blocks = 0
        self.cache_hit_blocks = 0
        self.cache_hit_tokens = 0
        self.cache_prompt_tokens = 0
        # TIERED KV (inference/kv_tiering.HostKVTier): a host-RAM second
        # tier behind the device prefix cache. Device-LRU evictions
        # spill (content key, frame) pairs into it; admission lookups
        # walk device-then-host, and host hits restore into fresh pool
        # blocks by async device_put overlapped with this step's decode
        # chunk. Strictly additive: None = exactly the single-tier
        # behavior, and the tier can never block allocation.
        self.host_tier = host_tier
        if host_tier is not None and not self.prefix_cache:
            raise ValueError(
                "host_tier requires prefix_cache=True — the tier is "
                "keyed by the prefix cache's content hashes")
        self._restores: Dict[int, _Restore] = {}
        self._pending_spills: List = []
        if host_tier is not None:
            # the caching pool reports each eviction BEFORE the frame
            # can be rewritten; the pairs queue here and flush as one
            # spill ahead of the next executor write
            pool.spill_sink = self._on_device_evict
        elif getattr(pool, "spill_sink", None) is not None:
            # a reused pool must not keep feeding a PREVIOUS session's
            # scheduler (tier-on then tier-off on the same executor)
            pool.spill_sink = None
        self.host_restores = 0
        self.host_hit_blocks = 0
        self.host_hit_tokens = 0
        self.host_restore_failures = 0
        self.host_spill_failures = 0
        # RETRY WITH BACKOFF (docs/SERVING.md "Admission control &
        # self-healing"): a failed restore is re-dispatched up to
        # ``restore_retries`` times with bounded exponential backoff +
        # deterministic jitter (hash of (rid, attempt)) before the
        # degrade-to-cold path fires; 0 = degrade immediately
        self.restore_retries = int(restore_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.restore_retry_count = 0
        # opt-in bounded READMISSION: a slot-attributed decode fault
        # restarts the request from its prompt (like preemption) up to
        # ``readmit_failed`` times before resolving FAILED
        self.readmit_failed = int(readmit_failed)
        self.readmissions = 0
        self._readmit_counts: Dict[Any, int] = {}
        self.last_restore_error: Optional[str] = None
        self.last_spill_error: Optional[str] = None
        # DISAGGREGATED SERVING (docs/SERVING.md): ``handoff`` makes
        # this a DECODE-role scheduler — the channel is drained at every
        # step boundary and its requests submit into the ordinary queue,
        # where admission's tiered lookup finds the frames the prefill
        # role published. ``publish_prefixes`` makes it a PREFILL-role
        # scheduler — every COMPLETED request's full prompt blocks are
        # pushed into the host tier at finish time, BEFORE the
        # completion is surfaced, so the handoff that follows can never
        # race the publish. Both ride the tier machinery above; neither
        # changes colocated behavior when unset.
        self.handoff = handoff
        self.publish_prefixes = bool(publish_prefixes)
        if self.publish_prefixes and host_tier is None:
            raise ValueError(
                "publish_prefixes=True needs a host_tier — published "
                "frames ARE the transfer")
        self.disagg_handoffs = 0
        self.disagg_degrades = 0
        self.disagg_restored = 0
        self.published_requests = 0
        self.published_blocks = 0
        self.tables = SlotBlockTables(num_slots, table_width, pool)
        self.queue: Deque[Request] = deque()
        self.slots = [_Slot() for _ in range(num_slots)]
        self.seq_lens = np.zeros(num_slots, np.int32)
        self.last_tokens = np.zeros(num_slots, np.int32)
        self.active = np.zeros(num_slots, bool)
        self.steps_left = np.zeros(num_slots, np.int32)
        # on-demand growth state: a stalled slot is active but excluded
        # from decode calls until the pool can cover its next write
        self.stalled = np.zeros(num_slots, bool)
        self._cap_steps = np.zeros(num_slots, np.int64)
        self.reserve_upfront = bool(reserve_upfront)
        self.preemptions = 0
        # --- fault tolerance ------------------------------------------------
        # bounded preemption: a request restart-from-prompt-ed more than
        # this many times resolves PREEMPTED_LIMIT instead of livelocking
        # (victim selection is preempt-count-aware, so the bound is only
        # reached when the pool genuinely cannot make progress)
        self.max_preemptions = int(max_preemptions)
        # default queue-wait bound (None = wait forever); per-request
        # Request.queue_timeout_s overrides
        self.queue_timeout_s = queue_timeout_s
        # invariant auditor cadence: cross-check refcounts/tables/free
        # lists/prefix index every N steps (0 disables; chaos tests run
        # with 1 — every chunk)
        self.audit_every = int(audit_every)
        self.last_audit_violations: List[str] = []
        self.fault_injector = fault_injector
        self._step_idx = 0
        self._cancelled: Set[Any] = set()
        self._preempt_counts: Dict[Any, int] = {}
        # per-step pool occupancy series for the bench artifact
        # (BENCH_SERVE.json) — None disables recording
        self.occupancy_log: Optional[List[dict]] = \
            [] if record_occupancy else None
        # per-step work split (decode tokens consumed / prefill tokens
        # fed this step), sampled into the occupancy series — the
        # decode-interference A/B's raw data
        self._step_decode_tokens = 0
        self._step_prefill_tokens = 0
        self._submit_times = {}
        # --- observability (deepspeed_tpu/observability) --------------------
        # metrics: a MetricsRegistry absorbing the serve counters/
        # histograms; tracer: a RequestTracer emitting lifecycle spans.
        # Both optional and strictly host-side — every emission below
        # sits at an existing host-call boundary, never inside jit.
        self.metrics = metrics
        self.tracer = tracer
        # slo: an observability.slo.SLOTracker ticked at chunk
        # boundaries (rolling-window burn rates + goodput); optional,
        # host-side, rate-limited internally
        self.slo = slo
        # admission: an inference.admission.AdmissionController
        # consulted at the top of every admit wave — under overload it
        # picks queued victims that resolve as structured REJECTED
        # completions (never exceptions, never in-flight slots)
        self.admission = admission
        # monotonic submit stamps for QUEUED spans (wall-clock
        # _submit_times stays the Completion API timebase)
        self._submit_mono: Dict[Any, float] = {}
        # high-water mark into fault_injector.log already traced
        self._fi_traced = 0

    # --- observability emission helpers ---------------------------------------
    def _trace_queued_end(self, rid: Any) -> None:
        """Close ``rid``'s QUEUED span — at admission, or at a terminal
        reached while still queued. Pops the monotonic submit stamp so
        the span is emitted exactly once per queue residency (a
        preemption re-stamps, giving the requeue its own span)."""
        t0 = self._submit_mono.pop(rid, None)
        tr = self.tracer
        if tr is not None and t0 is not None:
            tr.span("QUEUED", t0, tr.now(), rid=rid)

    def _obs_terminal(self, comp: Completion) -> Completion:
        """The one terminal emission every Completion passes through:
        a per-status completion counter, latency/TPOT histograms, and
        the trace's terminal event (chaos tests pin exactly one per
        request, status matching)."""
        m = self.metrics
        if m is not None:
            n = int(comp.tokens.size)
            m.inc(f"serve.completions.{comp.status}")
            m.inc("serve.tokens_generated", n)   # DELIVERED tokens
            if comp.status == COMPLETED:
                # goodput numerator: tokens delivered WITHIN deadline —
                # deadline enforcement resolves late streams TIMED_OUT,
                # so COMPLETED is exactly the in-deadline set. Dividing
                # by serve.tokens_sampled (work done, incl. preemption
                # regeneration) makes restart/timeout waste visible.
                m.inc("serve.tokens_delivered", n)
            sampled = m.counter("serve.tokens_sampled")
            if sampled:
                m.set_gauge("serve.goodput",
                            m.counter("serve.tokens_delivered") / sampled)
            m.observe("serve.latency_s",
                      max(0.0, comp.t_finish - comp.t_submit))
            if n > 0:
                # per-request latency breakdown lands HERE — once per
                # request, from the same Completion fields the bench
                # measures externally — so a preempted-and-regenerated
                # request contributes exactly one TTFT/queue-wait
                # sample (its final attempt's), never one per admission
                m.observe("serve.ttft_s",
                          max(0.0, comp.t_first_token - comp.t_submit))
                m.observe("serve.queue_wait_s",
                          max(0.0, comp.t_admitted - comp.t_submit))
            if comp.status == COMPLETED and n > 1 \
                    and comp.t_finish > comp.t_first_token:
                # time-per-output-token over the decode phase (first
                # token is TTFT's; the remaining n-1 are decode steps)
                m.observe("serve.tpot_s",
                          (comp.t_finish - comp.t_first_token) / (n - 1))
        if self.tracer is not None:
            self.tracer.terminal(comp.rid, comp.status,
                                 tokens=int(comp.tokens.size))
        return comp

    def _trace_chaos(self) -> None:
        """Mirror NEW fault-injector firings into the trace (the
        injector's log is the source of truth; this just replays the
        tail so auditor/chaos analysis lives in one timeline). The
        watermark lives ON the injector (``fi.traced``) so a
        ReplicaGroup sharing the injector can mirror replica-site
        firings without double-emitting the scheduler's."""
        fi, tr = self.fault_injector, self.tracer
        if fi is None or tr is None:
            return
        mark = max(getattr(fi, "traced", 0), self._fi_traced)
        for entry in fi.log[mark:]:
            detail = {k: v for k, v in entry.items() if k != "site"}
            tr.instant(f"CHAOS/{entry['site']}", cat="chaos", **detail)
        self._fi_traced = len(fi.log)
        if hasattr(fi, "traced"):
            fi.traced = len(fi.log)

    # --- queue ---------------------------------------------------------------
    def submit(self, req: Request, now: Optional[float] = None) -> None:
        need = blocks_for(len(req.prompt) + req.max_new_tokens,
                          self.pool.block_size)
        if need > self.tables.width:
            raise ValueError(
                f"request {req.rid}: needs {need} blocks "
                f"({len(req.prompt)}+{req.max_new_tokens} tokens) but the "
                f"serve config caps a slot at {self.tables.width} blocks — "
                f"raise max_context")
        if need > self.pool.num_blocks - 1:
            # backpressure waits for blocks to RECYCLE; a request larger
            # than the whole pool would wait forever (an unsatisfiable
            # FIFO head also starves everything behind it) — reject now
            raise ValueError(
                f"request {req.rid}: needs {need} blocks but the pool "
                f"only has {self.pool.num_blocks - 1} usable — raise "
                f"num_blocks")
        self._submit_times[req.rid] = (now if now is not None
                                       else time.time())
        if self.tracer is not None:
            # trace-replay submissions carry a future arrival: start the
            # QUEUED span at the nominal arrival, not the bulk submit
            t_m = self.tracer.now()
            if now is not None:
                t_m += max(0.0, now - time.time())
            self._submit_mono[req.rid] = t_m
        if self.metrics is not None:
            self.metrics.inc("serve.requests_submitted")
        self.queue.append(req)

    @property
    def busy(self) -> bool:
        return (bool(self.queue) or bool(self.active.any())
                or bool(self.prefilling.any()) or bool(self._restores)
                or (self.handoff is not None
                    and not self.handoff.done()))

    @property
    def restoring(self) -> np.ndarray:
        """Per-slot restore-in-flight mask, derived from ``_restores``
        — the pending-restore map is the single source of truth, so the
        mask can never desync from it."""
        m = np.zeros(self.num_slots, bool)
        if self._restores:
            m[list(self._restores)] = True
        return m

    # --- tiered KV: spill / restore ------------------------------------------
    def _on_device_evict(self, key: bytes, bid: int) -> None:
        """Eviction hook (PrefixCachingBlockPool.spill_sink): the frame
        behind ``bid`` is about to be handed to a new owner — queue it
        for a device→host spill. Fires inside ``pool.allocate``, where
        no device write can happen; the queue is flushed before the
        next executor call that could touch the frame."""
        self._pending_spills.append((key, bid))

    def _flush_spills(self) -> None:
        """Copy queued evicted frames to the host tier. MUST run before
        any executor call that writes pool blocks (prefill, decode,
        copy_blocks, finish_restore) — after that the frames belong to
        their new owners. A spill failure only LOSES cache content
        (those prefixes go cold); it never fails a request."""
        if not self._pending_spills:
            return
        entries, self._pending_spills = self._pending_spills, []
        try:
            self.executor.spill_blocks(entries)
            if self.metrics is not None:
                self.metrics.inc("serve.host_spill_blocks", len(entries))
            if self.tracer is not None:
                self.tracer.instant("SPILL", cat="tiering",
                                    blocks=len(entries))
        except Exception as e:
            self.host_spill_failures += len(entries)
            self.last_spill_error = str(e)
            if self.metrics is not None:
                self.metrics.inc("serve.host_spill_failures",
                                 len(entries))
            if self.tracer is not None:
                self.tracer.instant("SPILL_FAIL", cat="tiering",
                                    blocks=len(entries), error=str(e))

    # --- disaggregated serving: handoff / publish / degrade ------------------
    def _drain_handoffs(self, now: float) -> List[Completion]:
        """Admit requests a prefill-role replica handed off (their
        published frames are already in the shared tier — the put
        happens after the publish). Validation failures resolve
        REJECTED exactly like ``generate_stream``'s pre-submit checks:
        a handed-off request still gets its one terminal Completion."""
        done: List[Completion] = []
        for req in self.handoff.drain():
            self.disagg_handoffs += 1
            if self.metrics is not None:
                self.metrics.inc("serve.disagg.handoffs")
            if self.tracer is not None:
                self.tracer.instant("DISAGG_HANDOFF", cat="disagg",
                                    rid=req.rid,
                                    prompt_tokens=len(req.prompt))
            try:
                self.submit(req, now=now)
            except ValueError as e:
                done.append(self._obs_terminal(Completion(
                    rid=req.rid, prompt=req.prompt,
                    tokens=np.zeros(0, np.int32), t_submit=now,
                    t_admitted=now, t_first_token=now, t_finish=now,
                    status=REJECTED, error=str(e))))
        return done

    def _note_disagg_degrade(self, req: Request, reason: str) -> None:
        """A routed-prefill request is about to cold-prefill on the
        decode side — the transfer failed CLEANLY (frames evicted
        between publish and restore, restore refused/failed). Counted
        and traced, never a terminal: degrade-to-cold-prefill is the
        contract, the stream stays byte-identical."""
        self.disagg_degrades += 1
        if self.metrics is not None:
            self.metrics.inc("serve.disagg.degrades")
        if self.tracer is not None:
            self.tracer.instant("DISAGG_DEGRADE", cat="disagg",
                                rid=req.rid, reason=reason)

    def _publish_slot_prefix(self, slot_id: int) -> None:
        """PREFILL-role finish hook: push the slot's full prompt blocks
        into the host tier NOW (before the blocks release), making the
        tier the transfer — a decode-role admission that looks these
        keys up after the completion surfaces is guaranteed to find
        them (modulo the tier's own capacity eviction, which the decode
        side degrades through). Runs after ``_register_slot_prefix``,
        so the executor's spill gather dedups against frames the tier
        already holds via ``touch``."""
        slot = self.slots[slot_id]
        bs = self.pool.block_size
        blocks = self.tables.blocks_of(slot_id)
        n_full = min(slot.seq_len // bs, len(blocks))
        if n_full < 1:
            return
        stream = np.concatenate(
            [slot.req.prompt, np.asarray(slot.out, np.int32)])
        keys = block_content_keys(stream[:n_full * bs], bs,
                                  self.pool.salt)
        self._pending_spills.extend(zip(keys, blocks[:n_full]))
        self._flush_spills()
        self.published_requests += 1
        self.published_blocks += n_full
        if self.metrics is not None:
            self.metrics.inc("serve.disagg.published_requests")
            self.metrics.inc("serve.disagg.published_blocks", n_full)
        if self.tracer is not None:
            self.tracer.instant("DISAGG_PUBLISH", cat="disagg",
                                rid=slot.req.rid, blocks=n_full)

    def next_arrival(self) -> Optional[float]:
        """Earliest queued arrival_time, for idle waiting."""
        times = [r.arrival_time for r in self.queue
                 if r.arrival_time is not None]
        return min(times) if times else None

    # --- cancellation / deadlines --------------------------------------------
    def cancel(self, rid: Any) -> bool:
        """Cooperatively cancel a queued or in-flight request: it
        resolves ``CANCELLED`` at the next step boundary (its blocks
        release; with prefix caching, shared blocks only DEREF — other
        holders and the content index are untouched). Returns False for
        an unknown/already-finished rid (no pending-cancel is stored, so
        a recycled rid can never be killed by a stale cancel)."""
        known = any(r.rid == rid for r in self.queue) or \
            any(s.req is not None and s.req.rid == rid for s in self.slots)
        if known:
            self._cancelled.add(rid)
        return known

    def _terminal_queued(self, req: Request, status: str, error: str,
                         now: float,
                         t_admitted: Optional[float] = None) -> Completion:
        """Resolve a request that never produced tokens (cancel/timeout
        while queued, or a prefill that failed before its first token —
        the caller releases any blocks in that case): the one structured
        terminal result plus the forget-this-rid bookkeeping."""
        t_sub = self._submit_times.pop(req.rid, now)
        self._cancelled.discard(req.rid)
        self._preempt_counts.pop(req.rid, None)
        self._readmit_counts.pop(req.rid, None)
        self._trace_queued_end(req.rid)
        return self._obs_terminal(Completion(
            rid=req.rid, prompt=req.prompt,
            tokens=np.zeros(0, np.int32), t_submit=t_sub,
            t_admitted=now if t_admitted is None else t_admitted,
            t_first_token=now, t_finish=now,
            status=status, error=error))

    def _terminal_slot(self, slot_id: int, status: str, error: str,
                       now: float, register: bool = True) -> Completion:
        """Resolve an in-flight slot to a non-COMPLETED terminal: build
        the Completion (partial tokens attached), release every block
        (deref-only for shared prefix-cache blocks), clear the slot.
        ``register=False`` skips prefix registration — used when the
        KV's integrity is in doubt (executor faults)."""
        slot = self.slots[slot_id]
        req = slot.req
        if register:
            self._register_slot_prefix(slot_id)
        comp = self._obs_terminal(Completion(
            rid=req.rid, prompt=req.prompt,
            tokens=np.asarray(slot.out, np.int32),
            t_submit=self._submit_times.pop(req.rid, slot.t_admitted),
            t_admitted=slot.t_admitted, t_first_token=slot.t_first,
            t_finish=now, status=status, error=error))
        self._cancelled.discard(req.rid)
        self._preempt_counts.pop(req.rid, None)
        self._readmit_counts.pop(req.rid, None)
        self.tables.release(slot_id)
        self._clear_slot(slot_id)
        return comp

    def _deadline_of(self, req: Request) -> Optional[float]:
        if req.deadline_s is None:
            return None
        t_sub = self._submit_times.get(req.rid)
        return None if t_sub is None else t_sub + req.deadline_s

    def _reap(self, now: float) -> List[Completion]:
        """Apply cancellations, deadlines and queue-wait timeouts at the
        step boundary (the cooperative enforcement point: decode chunks
        are never interrupted mid-program). Runs BEFORE admission so a
        doomed queue head can never take a slot from a live request."""
        done: List[Completion] = []
        if self.queue:
            keep: Deque[Request] = deque()
            for req in self.queue:
                if req.rid in self._cancelled:
                    done.append(self._terminal_queued(
                        req, CANCELLED, "cancelled while queued", now))
                    continue
                dl = self._deadline_of(req)
                if dl is not None and now > dl:
                    done.append(self._terminal_queued(
                        req, TIMED_OUT,
                        f"deadline_s={req.deadline_s} expired while "
                        f"queued", now))
                    continue
                qt = req.queue_timeout_s if req.queue_timeout_s is not None \
                    else self.queue_timeout_s
                t_sub = self._submit_times.get(req.rid)
                if qt is not None and t_sub is not None \
                        and now - t_sub > qt:
                    done.append(self._terminal_queued(
                        req, TIMED_OUT,
                        f"queue wait exceeded {qt}s", now))
                    continue
                keep.append(req)
            self.queue = keep
        for slot_id, slot in enumerate(self.slots):
            if slot.req is None:
                continue
            if slot.req.rid in self._cancelled:
                done.append(self._terminal_slot(
                    slot_id, CANCELLED, "cancelled mid-stream", now))
                continue
            dl = self._deadline_of(slot.req)
            if dl is not None and now > dl:
                done.append(self._terminal_slot(
                    slot_id, TIMED_OUT,
                    f"deadline_s={slot.req.deadline_s} expired "
                    f"mid-stream", now))
        return done

    # --- admission -----------------------------------------------------------
    def _free_blocks(self) -> int:
        """The pool capacity this step may claim — the injector's pool
        windows read as 0 (allocation-side starvation: the exhaustion
        ladder is stall → total-stall → bounded preemption, never a
        crash)."""
        if self.fault_injector is not None \
                and self.fault_injector.pool_exhausted(self._step_idx):
            return 0
        return self.pool.num_free

    def _shed_queue(self, now: float) -> List[Completion]:
        """Consult the admission controller over the current queue: its
        victims resolve as structured REJECTED terminals (one per
        request, through the ordinary ``_terminal_queued`` path), the
        rest stay for the admit loop. In-flight slots are never shed."""
        ctrl = self.admission
        if ctrl is None:
            return []
        fi = self.fault_injector
        storm = (fi is not None
                 and fi.admission_storm(self._step_idx))
        pool_free = self.pool.num_free / max(1, self.pool.num_blocks)
        if not self.queue:
            # still re-evaluate: the hysteresis gauge recovers and the
            # SLO windows tick even between admission waves
            ctrl.update(queue_depth=0, pool_free_frac=pool_free,
                        storm=storm)
            return []
        victims = ctrl.shed(list(self.queue),
                            queue_depth=len(self.queue),
                            pool_free_frac=pool_free, storm=storm)
        if not victims:
            return []
        shed_rids = {id(r) for r, _ in victims}
        self.queue = deque(r for r in self.queue
                           if id(r) not in shed_rids)
        return [self._terminal_queued(req, REJECTED, reason, now)
                for req, reason in victims]

    def _admit(self, now: float) -> List[Completion]:
        done = self._shed_queue(now)
        for slot_id, slot in enumerate(self.slots):
            if not self.queue or not slot.free:
                continue
            if self._free_blocks() == 0:
                break                  # injected/real exhaustion: queue
            req = self.queue[0]
            if req.arrival_time is not None and req.arrival_time > now:
                break                  # FIFO: later requests wait too
            # on-demand: admission claims only the PROMPT's blocks (the
            # KV prefill writes now); generation capacity grows at
            # decode-chunk boundaries. reserve_upfront restores the old
            # worst-case claim for A/B runs.
            admit_tokens = len(req.prompt)
            if self.reserve_upfront:
                admit_tokens += req.max_new_tokens
            start, copy_pairs = 0, []
            host_keys: List[bytes] = []
            if self.prefix_cache:
                bs = self.pool.block_size
                keys = block_content_keys(req.prompt, bs, self.pool.salt)
                matched = self.pool.lookup(keys)
                if matched and len(matched) * bs >= len(req.prompt):
                    # whole prompt cached (block-aligned prompt): the last
                    # token must still be recomputed — its logits seed
                    # sampling — and it lands INSIDE the last cached
                    # block, so that one is copy-on-write instead of
                    # shared (1-token prefill into a private copy beats
                    # re-prefilling the whole block)
                    shared, cow_src = matched[:-1], matched[-1]
                    start = len(req.prompt) - 1
                else:
                    shared, cow_src = matched, None
                    start = len(shared) * bs
                res = self.tables.assign_cached(slot_id, shared,
                                                admit_tokens,
                                                cow_src=cow_src)
                if res is None:
                    break              # backpressure: queue, don't crash
                copy_pairs = res
                self.cache_lookup_blocks += len(keys)
                self.cache_hit_blocks += len(matched)
                self.cache_hit_tokens += start
                self.cache_prompt_tokens += len(req.prompt)
            else:
                need = blocks_for(admit_tokens, self.pool.block_size)
                if need > self._free_blocks():
                    break              # backpressure: queue, don't crash
                self.tables.assign(slot_id, admit_tokens)
            self.queue.popleft()
            t_admit = time.time()
            self._trace_queued_end(req.rid)
            if self.metrics is not None:
                # operational counter (re-admissions after preemption
                # count again); the per-request queue_wait_s histogram
                # is observed once, at the terminal (_obs_terminal)
                self.metrics.inc("serve.admissions")
            # allocation above may have evicted cached blocks — their
            # frames must reach the host tier before ANY executor call
            # can write pool blocks (CoW copy, prefill)
            self._flush_spills()
            if self.prefix_cache and self.host_tier is not None \
                    and cow_src is None and len(matched) < len(keys):
                # TIERED lookup: where the device index stops, the host
                # tier continues (same chained keys, so the walk stays
                # a contiguous prefix). Host hits restore into FRESH
                # blocks below — private to this slot, so no CoW is
                # ever needed on them. AFTER admission + spill flush:
                # the tier's monotonic hit/miss counters see each
                # request once (a queue-head retry under backpressure
                # must not re-count), and frames this very allocation
                # just evicted are already host-hittable.
                host_keys = self.host_tier.lookup(keys[len(matched):])
            if req.routed_prefill:
                # the prefill role published this prompt — anything the
                # two-tier walk fails to cover will cold-prefill here,
                # which is exactly the degrade contract (frames evicted
                # between publish and restore, tier capacity, etc.)
                if not self.prefix_cache:
                    self._note_disagg_degrade(
                        req, "decode replica has no prefix cache")
                else:
                    covered_blocks = len(matched) + len(host_keys)
                    if covered_blocks < len(keys):
                        self._note_disagg_degrade(
                            req, f"transfer covers {covered_blocks}/"
                            f"{len(keys)} prompt blocks")
            if host_keys:
                blocks = self.tables.blocks_of(slot_id)
                targets = blocks[len(shared):len(shared) + len(host_keys)]
                entries = list(zip(host_keys, targets))
                covered = (len(shared) + len(host_keys)) * bs
                handle = None
                try:
                    self.executor.set_slot(slot_id, req)
                    handle = self.executor.begin_restore(slot_id, entries)
                except Exception as e:
                    # a restore that won't even start degrades to a cold
                    # prefill below — never a request failure
                    self.last_restore_error = f"begin_restore: {e}"
                    handle = None
                if handle is not None:
                    # RESTORE-IN-FLIGHT: the slot is admitted (blocks
                    # held, req bound) but sits out this step's decode —
                    # the host→device transfer dispatched above overlaps
                    # that chunk, and the next step boundary lands the
                    # frames and prefills only the uncovered tail
                    slot.req = req
                    slot.t_admitted = t_admit
                    slot.t_first = t_admit
                    self._restores[slot_id] = _Restore(
                        req=req, handle=handle, entries=entries,
                        start=min(covered, len(req.prompt) - 1),
                        dev_start=start, t_admit=t_admit,
                        t_mono=(self.tracer.now()
                                if self.tracer is not None else 0.0))
                    if self.metrics is not None:
                        self.metrics.inc("serve.restores_dispatched")
                    continue
                self.host_restore_failures += 1
                if self.metrics is not None:
                    self.metrics.inc("serve.host_restore_failures")
                if req.routed_prefill:
                    self._note_disagg_degrade(
                        req, "begin_restore refused the transfer")
            if self.chunk_tokens:
                # chunked prefill: bind the slot (CoW before the first
                # write, same isolation envelope) but feed NO tokens yet
                # — this step's ragged call assigns the first chunk
                failed = self._begin_chunked_prefill(
                    slot_id, req, start, t_admit, bind=True,
                    copy_pairs=copy_pairs)
                if failed is not None:
                    done.append(failed)
                continue
            first, failed = self._prefill_slot(slot_id, req, start,
                                               t_admit, bind=True,
                                               copy_pairs=copy_pairs)
            if failed is not None:
                done.append(failed)
                continue
            done.extend(self._activate_slot(slot_id, req, first, t_admit))
        return done

    def _begin_chunked_prefill(self, slot_id: int, req: Request,
                               start: int, t_admit: float,
                               bind: bool = False,
                               copy_pairs=None) -> Optional[Completion]:
        """Chunked-mode admission epilogue (and restore-landing
        epilogue): bind the slot's executor state under the per-request
        isolation contract and mark it PREFILLING from ``start`` — the
        ragged step then feeds its prompt in chunks at step boundaries.
        Returns a FAILED Completion when binding/CoW raised (blocks
        released, slot immediately admissible), else None."""
        slot = self.slots[slot_id]
        try:
            if bind:
                self.executor.set_slot(slot_id, req)
                if copy_pairs:
                    self.executor.copy_blocks(copy_pairs)
        except Exception as e:
            self.tables.release(slot_id)
            self._clear_slot(slot_id)
            return self._terminal_queued(
                req, FAILED, f"executor prefill error: {e}",
                time.time(), t_admitted=t_admit)
        slot.req = req
        slot.out = []
        slot.seq_len = int(start)
        slot.remaining = req.max_new_tokens
        slot.t_admitted = t_admit
        slot.t_first = t_admit
        self.seq_lens[slot_id] = int(start)
        self.prefilling[slot_id] = True
        self._prefill_next[slot_id] = int(start)
        return None

    def _prefill_slot(self, slot_id: int, req: Request, start: int,
                      t_admit: float, bind: bool = False,
                      copy_pairs=None):
        """Run the slot's prefill (tail-only when ``start``) under the
        PER-REQUEST ISOLATION contract, shared by direct admission and
        the finish-restore paths: any executor error resolves THIS
        request FAILED — its blocks release (shared prefix blocks only
        deref) and the slot is immediately admissible again, so
        co-scheduled slots never see the fault. No prefix registration:
        the KV behind a failed prefill is not trustworthy content.
        ``bind`` runs the admission-path slot binding inside the same
        isolation envelope (the finish-restore path bound its slot at
        ``begin_restore`` time). Returns ``(first_token, None)`` on
        success or ``(None, FAILED Completion)``."""
        tr = self.tracer
        t0_m = tr.now() if tr is not None else 0.0
        t0_w = time.time()
        try:
            if bind:
                self.executor.set_slot(slot_id, req)
                if copy_pairs:
                    # device-side CoW duplication BEFORE the slot's first
                    # write (and before any allocation could evict the
                    # source) — executors serving a prefix-cache scheduler
                    # must implement copy_blocks
                    self.executor.copy_blocks(copy_pairs)
            if self.fault_injector is not None:
                self.fault_injector.before_prefill(
                    self._step_idx, slot_id, req.rid)
            first = int(
                self.executor.prefill(slot_id, req.prompt,
                                      self.tables.table[slot_id],
                                      start)
                if start else
                self.executor.prefill(slot_id, req.prompt,
                                      self.tables.table[slot_id]))
            if tr is not None:
                tr.span("PREFILL", t0_m, tr.now(),
                        tid=1 + slot_id, rid=req.rid, slot=slot_id,
                        start=int(start), tokens=len(req.prompt) - start)
            if self.metrics is not None:
                self.metrics.observe("serve.prefill_s",
                                     time.time() - t0_w)
            self._step_prefill_tokens += len(req.prompt) - int(start)
            return first, None
        except Exception as e:
            if tr is not None:
                tr.span("PREFILL", t0_m, tr.now(),
                        tid=1 + slot_id, rid=req.rid, slot=slot_id,
                        start=int(start), error=str(e))
            self.tables.release(slot_id)
            self._clear_slot(slot_id)
            return None, self._terminal_queued(
                req, FAILED, f"executor prefill error: {e}",
                time.time(), t_admitted=t_admit)

    def _activate_slot(self, slot_id: int, req: Request, first: int,
                       t_admit: float) -> List[Completion]:
        """Post-prefill slot bring-up, shared by direct admission and
        the finish-restore path: bind the slot state, EAGERLY register
        the prompt's full blocks (requests sharing a prefix that are
        admitted later THIS STEP — or any step while this slot still
        decodes — already hit; registration only at completion would
        miss every concurrent burst), then activate for decode or
        retire immediately (1-token budgets, eos on the first token)."""
        slot = self.slots[slot_id]
        t_first = time.time()
        slot.req = req
        slot.seq_len = len(req.prompt)
        slot.remaining = req.max_new_tokens - 1
        slot.out = [first]
        slot.t_admitted = t_admit
        slot.t_first = t_first
        self.seq_lens[slot_id] = slot.seq_len
        self.last_tokens[slot_id] = first
        self._register_slot_prefix(slot_id)
        if self.metrics is not None:
            # work-done counters (a preempted request's regenerated
            # tokens count again — honest compute accounting); the
            # DELIVERED-token counter and the per-request TTFT sample
            # land once, at the terminal (_obs_terminal)
            self.metrics.inc("serve.prefills")
            self.metrics.inc("serve.tokens_sampled")
        hit_eos = req.eos_id >= 0 and first == req.eos_id
        if slot.remaining == 0 or hit_eos:
            return [self._finish(slot_id, t_first)]
        self.active[slot_id] = True
        self.steps_left[slot_id] = slot.remaining
        return []

    def _finish_restores(self, now: float) -> List[Completion]:
        """Land every restore dispatched on a PREVIOUS step: the staged
        host→device transfer had that step's decode chunk to hide
        behind, so finishing here (scatter + tail prefill) is the
        overlap paying off. A failed restore (transfer error, tier
        eviction race, injected fault) DEGRADES the request to a cold
        prefill from its device-matched start — the blocks are already
        private to the slot, the recompute overwrites whatever the
        failed transfer left, and co-scheduled streams never notice.
        Prefill errors keep the admission path's per-request isolation
        (FAILED, blocks released, slot immediately admissible)."""
        if not self._restores:
            return []
        done: List[Completion] = []
        fi = self.fault_injector
        tr = self.tracer
        for slot_id in sorted(self._restores):
            st = self._restores[slot_id]
            if st.retry_at > time.monotonic():
                continue               # backoff: lands on a later step
            self._restores.pop(slot_id)
            req = st.req
            self._flush_spills()       # frames must land before scatter
            ok = False
            try:
                if fi is not None:
                    delay = fi.restore_delay(self._step_idx, req.rid)
                    if delay > 0:
                        time.sleep(delay)
                    fi.before_restore(self._step_idx, slot_id, req.rid)
                ok = bool(self.executor.finish_restore(st.handle))
            except RequestFault as e:
                # attributed PRE-transfer failure (the injector's
                # stand-in for a refused device_put): pools untouched,
                # so this one request degrades to a cold prefill
                self.last_restore_error = str(e)
                ok = False
            except Exception as e:
                # the jitted scatter consumed the DONATED pools and
                # died — their state is unknown, exactly the
                # unattributed-decode-error case: fail this request
                # and every runnable slot; queued requests keep serving
                self.last_restore_error = str(e)
                self.host_restore_failures += 1
                if self.metrics is not None:
                    self.metrics.inc("serve.host_restore_failures")
                if tr is not None:
                    tr.span("RESTORING", st.t_mono, tr.now(),
                            tid=1 + slot_id, rid=req.rid, slot=slot_id,
                            blocks=len(st.entries), ok=False,
                            error=str(e))
                t_err = time.time()
                self.tables.release(slot_id)
                self._clear_slot(slot_id)
                done.append(self._terminal_queued(
                    req, FAILED, f"executor restore error: {e}", t_err,
                    t_admitted=st.t_admit))
                done.extend(self._on_decode_error(
                    RuntimeError(f"restore scatter failed: {e}"),
                    np.logical_and(self.active, ~self.stalled), t_err))
                # the OTHER pending restores would land on those same
                # unknown-state pools — their shared-prefix KV is just
                # as suspect, so they join the blast radius instead of
                # completing with silently corrupt context
                for s2 in sorted(self._restores):
                    st2 = self._restores[s2]
                    self.host_restore_failures += 1
                    if self.metrics is not None:
                        self.metrics.inc("serve.host_restore_failures")
                    if tr is not None:
                        # the sibling's restore also ends here — close
                        # its RESTORING span so the trace shows the
                        # full interval, not admitted→terminal with a
                        # hole exactly where the failure needs debugging
                        tr.span("RESTORING", st2.t_mono, tr.now(),
                                tid=1 + s2, rid=st2.req.rid, slot=s2,
                                blocks=len(st2.entries), ok=False,
                                error=str(e))
                    self.tables.release(s2)
                    self._clear_slot(s2)       # drops the handle
                    done.append(self._terminal_queued(
                        st2.req, FAILED,
                        f"executor restore error: {e}", t_err,
                        t_admitted=st2.t_admit))
                break
            if not ok and st.attempt < self.restore_retries:
                # RETRY WITH BACKOFF: re-dispatch the transfer instead
                # of degrading — bounded exponential delay with
                # deterministic jitter (crc32 of (rid, attempt), so a
                # replayed chaos plan backs off identically), landing
                # at the first step boundary past ``retry_at``
                handle = None
                try:
                    handle = self.executor.begin_restore(slot_id,
                                                         st.entries)
                except Exception as e:
                    self.last_restore_error = f"begin_restore retry: {e}"
                if handle is not None:
                    seed = zlib.crc32(
                        repr((req.rid, st.attempt)).encode())
                    jitter = (seed % 1000) / 2000.0       # [0, 0.5)
                    delay = (self.retry_backoff_s * (2 ** st.attempt)
                             * (1.0 + jitter))
                    st.handle = handle
                    st.attempt += 1
                    st.retry_at = time.monotonic() + delay
                    self._restores[slot_id] = st
                    self.restore_retry_count += 1
                    if self.metrics is not None:
                        self.metrics.inc("serve.restore_retries")
                    if tr is not None:
                        tr.instant("RESTORE_RETRY", cat="serve",
                                   rid=req.rid, slot=slot_id,
                                   attempt=st.attempt,
                                   delay_s=round(delay, 4))
                    continue
            if tr is not None:
                tr.span("RESTORING", st.t_mono, tr.now(),
                        tid=1 + slot_id, rid=req.rid, slot=slot_id,
                        blocks=len(st.entries), ok=bool(ok))
            if self.metrics is not None:
                self.metrics.inc("serve.host_restores" if ok
                                 else "serve.host_restore_failures")
            if ok:
                start = st.start
                self.host_restores += 1
                self.host_hit_blocks += len(st.entries)
                self.host_hit_tokens += st.start - st.dev_start
                # host-restored tokens skip prefill exactly like device
                # hits — they count toward the same token hit-rate
                self.cache_hit_tokens += st.start - st.dev_start
                if req.routed_prefill:
                    # the handed-off request landed already-prefilled —
                    # the disaggregation payoff, counted per request
                    self.disagg_restored += 1
                    if self.metrics is not None:
                        self.metrics.inc("serve.disagg.restored")
            else:
                start = st.dev_start
                self.host_restore_failures += 1
                if req.routed_prefill:
                    self._note_disagg_degrade(
                        req, "restore failed on the decode side")
            if self.chunk_tokens:
                # the restored slot enters PREFILLING at its covered
                # offset — the ragged step feeds the uncovered tail in
                # chunks starting this very step (set_slot already ran
                # at begin_restore time)
                failed = self._begin_chunked_prefill(
                    slot_id, req, start, st.t_admit)
                if failed is not None:
                    done.append(failed)
                continue
            first, failed = self._prefill_slot(slot_id, req, start,
                                               st.t_admit)
            if failed is not None:
                done.append(failed)
                continue
            done.extend(self._activate_slot(slot_id, req, first,
                                            st.t_admit))
        return done

    # --- completion ----------------------------------------------------------
    def _register_slot_prefix(self, slot_id: int) -> None:
        """Index the slot's FULL blocks by content (prompt + generated
        tokens whose KV is written). Shared blocks already carry these
        keys (register no-ops); a private block whose content duplicates
        an indexed one simply stays unregistered and frees normally —
        first writer wins, no device copy for dedup."""
        if not self.prefix_cache:
            return
        slot = self.slots[slot_id]
        bs = self.pool.block_size
        blocks = self.tables.blocks_of(slot_id)
        n_full = min(slot.seq_len // bs, len(blocks))
        if n_full < 1:
            return
        # KV at position p holds token p of prompt++generated (the last
        # sampled token's KV is never written, so seq_len bounds this)
        stream = np.concatenate(
            [slot.req.prompt, np.asarray(slot.out, np.int32)])
        keys = block_content_keys(stream[:n_full * bs], bs,
                                  self.pool.salt)
        for key, bid in zip(keys, blocks[:n_full]):
            self.pool.register(key, bid)

    def _finish(self, slot_id: int, t_finish: float) -> Completion:
        slot = self.slots[slot_id]
        req = slot.req
        comp = self._obs_terminal(Completion(
            rid=req.rid, prompt=req.prompt,
            tokens=np.asarray(slot.out, np.int32),
            t_submit=self._submit_times.pop(req.rid, slot.t_admitted),
            t_admitted=slot.t_admitted, t_first_token=slot.t_first,
            t_finish=t_finish))
        self._cancelled.discard(req.rid)
        self._preempt_counts.pop(req.rid, None)
        self._readmit_counts.pop(req.rid, None)
        # index full blocks (now including generated content — a future
        # prompt that embeds this completion, e.g. a multi-turn
        # continuation, prefills only its new tokens) BEFORE releasing:
        # at ref 0 registered blocks park on the cache LRU, unregistered
        # ones free
        self._register_slot_prefix(slot_id)
        if self.publish_prefixes:
            # prefill role: the prompt's frames reach the transfer tier
            # before this completion can trigger the decode-side handoff
            self._publish_slot_prefix(slot_id)
        self.tables.release(slot_id)   # blocks recycle to the pool
        self._clear_slot(slot_id)
        return comp

    def _clear_slot(self, slot_id: int) -> None:
        slot = self.slots[slot_id]
        slot.req = None
        slot.out = []
        slot.seq_len = 0
        slot.remaining = 0
        self.active[slot_id] = False
        self.stalled[slot_id] = False
        self.prefilling[slot_id] = False
        self._prefill_next[slot_id] = 0
        self.steps_left[slot_id] = 0
        self.seq_lens[slot_id] = 0
        self.last_tokens[slot_id] = 0
        # a cancelled/timed-out RESTORING slot drops its in-flight
        # handle here — the staged transfer is simply never landed
        # (finish_restore not called), so the pools are untouched
        self._restores.pop(slot_id, None)

    # --- on-demand growth / preemption ----------------------------------------
    def _grow(self, slot_ids, horizon: int) -> None:
        """Grow each slot's table to cover the KV it will write in a
        decode call of up to ``horizon`` steps; mark slots the pool
        cannot cover as STALLED (resume is just this method succeeding
        on a later step). Updates ``_cap_steps`` — the per-slot write
        headroom the decode cap is derived from."""
        bs = self.pool.block_size
        for slot_id in slot_ids:
            slot = self.slots[slot_id]
            if slot.free or not self.active[slot_id]:
                continue
            cur = self.tables.num_blocks_of(slot_id)
            if not self.reserve_upfront:
                want = min(horizon, slot.remaining)
                need = blocks_for(slot.seq_len + want, bs) - cur
                if need > 0:
                    take = min(need, self._free_blocks(),
                               self.tables.width - cur)
                    if take > 0:
                        self.tables.grow(slot_id, take)
                        cur += take
            cap = cur * bs - slot.seq_len
            self._cap_steps[slot_id] = cap
            now_stalled = cap <= 0
            if now_stalled and not self.stalled[slot_id]:
                # transition INTO a stall — pool could not cover the
                # slot's next write (the exhaustion ladder's first rung)
                if self.metrics is not None:
                    self.metrics.inc("serve.stalls")
                if self.tracer is not None:
                    self.tracer.instant(
                        "STALL", tid=1 + slot_id, slot=slot_id,
                        rid=slot.req.rid, seq_len=int(slot.seq_len))
            self.stalled[slot_id] = now_stalled

    def _trim_spec_tail(self, slot_id: int) -> None:
        """Speculative ROLLBACK, block side: after a verify round the
        slot's true write position is ``seq_len`` (accepted prefix +
        bonus token); blocks grown to cover the rejected part of the
        1+K window go straight back to the pool so a wrong draft never
        holds capacity a neighbor (or the queue head) needs. The tail
        blocks are this step's fresh ``grow`` allocations — private
        (ref 1) and unregistered mid-decode — so the release frees them
        outright and can never rewrite a shared frame; under
        ``reserve_upfront`` the slot's full-horizon claim is its
        admission contract and nothing trims. The KV written into the
        rejected positions is stale-by-construction: ``col <= row_pos``
        masks it and the next accepted write overwrites it (the same
        invariant chunked prefill relies on)."""
        if self.reserve_upfront:
            return
        slot = self.slots[slot_id]
        keep = blocks_for(slot.seq_len, self.pool.block_size)
        freed = self.tables.trim(slot_id, keep)
        if freed:
            # the freed coverage is gone — next step's _grow re-extends
            self._cap_steps[slot_id] = keep * self.pool.block_size \
                - slot.seq_len

    def _preempt_for_progress(self, now: float) -> Optional[Completion]:
        """Total-stall safety valve: every active slot needs a block and
        the pool has none (possible only with >= 2 slots — submit()
        rejects requests larger than the whole pool, so a lone slot
        always fits). Evict one slot: its blocks recycle NOW (letting
        the others resume) and its request requeues at the FIFO head
        for a fresh admission — generation restarts from the prompt
        (greedy output identical; sampled streams restart from their
        seed).

        Victim selection is PREEMPT-AGE-AWARE: among active slots, pick
        the one whose request has been preempted FEWEST times (ties:
        most recently admitted — the classic youngest-first). A request
        that keeps losing the youngest race therefore stops being the
        victim after its first eviction, so repeated total stalls rotate
        victims instead of starving one request forever. The rotation is
        BOUNDED: a request past ``max_preemptions`` restarts resolves to
        a deterministic ``PREEMPTED_LIMIT`` terminal (partial tokens of
        the current attempt attached) instead of livelocking — returned
        here, None when the victim was requeued normally."""
        victim = max((s for s in range(self.num_slots) if self.active[s]),
                     key=lambda s: (
                         -self._preempt_counts.get(self.slots[s].req.rid, 0),
                         self.slots[s].t_admitted, s))
        req = self.slots[victim].req
        self.preemptions += 1
        count = self._preempt_counts.get(req.rid, 0) + 1
        self._preempt_counts[req.rid] = count
        if self.metrics is not None:
            self.metrics.inc("serve.preemptions")
        if self.tracer is not None:
            self.tracer.instant("PREEMPT", tid=1 + victim, slot=victim,
                                rid=req.rid, count=count)
        if count > self.max_preemptions:
            return self._terminal_slot(
                victim, PREEMPTED_LIMIT,
                f"preempted {count} times "
                f"(max_preemptions={self.max_preemptions})", now)
        # register before releasing: the victim's prompt blocks park on
        # the cache LRU instead of freeing, so its restart-from-prompt
        # readmission hits its OWN prefix and re-prefills only the
        # partial tail (unless pool pressure evicted the blocks first —
        # the cache never outranks a grow)
        self._register_slot_prefix(victim)
        self.tables.release(victim)
        self._clear_slot(victim)
        if self.tracer is not None:
            # the requeue opens a fresh QUEUED span (the wall-clock
            # submit time — hence queue_wait/TTFT accounting — is the
            # ORIGINAL one; the trace shows each residency separately)
            self._submit_mono[req.rid] = self.tracer.now()
        self.queue.appendleft(req)     # keeps original submit time
        return None

    def _record_occupancy(self, now: float) -> None:
        if self.occupancy_log is None:
            return
        # what the PR-1 upfront policy would pin for the SAME residency —
        # the per-step visualization of the reservation→on-demand win
        reserved_equiv = sum(
            blocks_for(len(s.req.prompt) + s.req.max_new_tokens,
                       self.pool.block_size)
            for s in self.slots if s.req is not None)
        self.occupancy_log.append({
            "t": now,
            "t_wall": time.time(),
            "blocks_allocated": self.pool.num_allocated,
            "blocks_reserved_equiv": reserved_equiv,
            "blocks_cached": getattr(self.pool, "num_cached", 0),
            "blocks_free": self.pool.num_free,
            "live_tokens": int(self.seq_lens.sum()),
            "active_slots": int(self.active.sum()),
            "stalled_slots": int(self.stalled.sum()),
            "prefilling_slots": int(self.prefilling.sum()),
            "queued": len(self.queue),
            # per-step work split — the decode-interference A/B's
            # evidence that chunked prefill keeps decode emitting
            # (bench.py --serve, detail.chunked_prefill_ab)
            "decode_tokens": int(self._step_decode_tokens),
            "prefill_tokens": int(self._step_prefill_tokens),
        })

    # --- one scheduling iteration --------------------------------------------
    def step(self, now: Optional[float] = None) -> List[Completion]:
        """Reap cancels/deadlines, grow in-flight tables, admit what
        fits, run one decode call, retire finished slots. Returns
        completions resolved this step — COMPLETED and non-COMPLETED
        terminals alike (possibly empty)."""
        now = time.time() if now is None else now
        self._step_idx += 1
        self._step_decode_tokens = 0
        self._step_prefill_tokens = 0
        fi = self.fault_injector
        if fi is not None:
            for rid in fi.cancels(self._step_idx):
                self.cancel(rid)
        # handed-off requests join the queue FIRST so this very step's
        # admission can restore them (their frames are already published)
        done = (self._drain_handoffs(now)
                if self.handoff is not None else [])
        # cancellation/deadline enforcement point: chunk boundaries only
        done.extend(self._reap(now))
        # land restores dispatched last step (their transfer overlapped
        # that step's decode) BEFORE growth/admission: the finished slot
        # joins this step's decode and its registered prefix is already
        # hittable by this step's admissions
        done.extend(self._finish_restores(now))
        # chunked mode decodes exactly ONE step per ragged call (the
        # mixed batch is the amortization), so its growth horizon is 1;
        # a speculative step can consume up to 1+K tokens per slot, so
        # its horizon covers the whole verify window (a partial grant
        # just clips the draft — the slot still decodes its 1 token)
        if self.spec:
            chunk = 1 + self.draft_len
        elif self.chunk_tokens:
            chunk = 1
        else:
            chunk = max(1, int(getattr(self.executor, "decode_chunk", 1)))
        # growth FIRST: in-flight slots outrank the queue head for free
        # blocks — admitting ahead of mid-decode grows would convert
        # pool pressure into stalls of already-running requests
        pre = [s for s in range(self.num_slots) if self.active[s]]
        self._grow(pre, chunk)
        done.extend(self._admit(now))
        pre_set = set(pre)
        self._grow([s for s in range(self.num_slots)
                    if self.active[s] and s not in pre_set], chunk)
        if self.chunk_tokens or self.spec:
            # the ragged path: chunked prefill and/or speculative verify
            # rows ride ONE executor call per step. In legacy-prefill
            # speculative sessions (chunk_tokens == 0) admission still
            # runs the split prefill programs, so ``prefilling`` is
            # never set and _chunked_step reduces to decode/verify rows.
            if self.active.any() or self.prefilling.any():
                done.extend(self._chunked_step(now))
            self._finish_step(now)
            return done
        if not self.active.any():
            self._finish_step(now)
            return done
        runnable = np.logical_and(self.active, ~self.stalled)
        if not runnable.any():
            # every active slot is stalled on an empty pool: preempt one
            # (age-aware, bounded) so the others resume THIS step
            term = self._preempt_for_progress(now)
            if term is not None:
                done.append(term)
            self._grow([s for s in range(self.num_slots)
                        if self.active[s]], chunk)
            runnable = np.logical_and(self.active, ~self.stalled)
            if not runnable.any():     # defensive: one preemption frees
                self._finish_step(now)          # >= 1 block by invariant
                return done
        # adaptive decode quantum: chunked executors amortize host round
        # trips over several steps, but while the QUEUE holds admissible
        # work the call must stop at the next slot completion — otherwise
        # a freed slot idles to the chunk boundary and the occupancy win
        # this scheduler exists for quantizes away
        max_steps = None
        if self.queue:
            max_steps = int(self.steps_left[runnable].min())
        if self._restores:
            # a dispatched restore lands at the NEXT boundary, so the
            # chunk length is the restored request's time-to-first-
            # token: one decode step is all the overlap the transfer
            # needs (the jitted scatter queues behind the device_put on
            # the device timeline regardless), while a full chunk would
            # hold that first token hostage to co-scheduled decode
            max_steps = 1 if max_steps is None else min(max_steps, 1)
        # on-demand coverage cap: the program must not write KV past the
        # blocks granted this step (partial grows shorten the call; the
        # next step grows again)
        feasible = int(self._cap_steps[runnable].min())
        planned = chunk if max_steps is None else min(chunk, max_steps)
        if feasible < planned:
            max_steps = feasible
        eff_steps = self.steps_left.copy()
        eff_steps[self.stalled] = 0        # stalled slots must not write
        # growth allocations above may have evicted cached blocks —
        # spill their frames before the decode program writes the pool
        self._flush_spills()
        tr = self.tracer
        t_dec0 = tr.now() if tr is not None else 0.0
        t_dec0_w = time.time()
        try:
            if fi is not None:
                delay = fi.chunk_delay(self._step_idx)
                if delay > 0:
                    time.sleep(delay)
                fi.before_decode(self._step_idx)
            toks = np.asarray(self.executor.decode(
                self.last_tokens.copy(), self.tables.table,
                self.seq_lens.copy(), runnable.copy(),
                eff_steps, max_steps), np.int32)
        except Exception as e:
            if tr is not None:
                tr.span("DECODE", t_dec0, tr.now(), cat="executor",
                        step=self._step_idx, error=str(e))
            # PER-REQUEST ISOLATION (mid-decode): the call failed as a
            # whole, so NO slot consumed tokens this step. A
            # slot-attributed RequestFault fails exactly that request;
            # an unattributed exception fails every runnable slot (the
            # scheduler cannot know whose state is corrupt). Either way
            # the queue keeps serving and serve() never raises.
            done.extend(self._on_decode_error(e, runnable, now))
            self._finish_step(now)
            return done
        if toks.ndim == 1:
            toks = toks[:, None]
        t_now = time.time()
        t_dec1 = tr.now() if tr is not None else 0.0
        if self.metrics is not None:
            self.metrics.inc("serve.decode_calls")
            self.metrics.observe("serve.decode_chunk_s",
                                 max(0.0, t_now - t_dec0_w))
        for slot_id, slot in enumerate(self.slots):
            if not runnable[slot_id]:
                continue
            rid = slot.req.rid
            consumed = 0
            for tok in toks[slot_id]:
                if slot.remaining <= 0:
                    break              # chunked executor overshoot: ignore
                self._consume_token(slot_id, int(tok))
                consumed += 1
            if consumed:
                self._step_decode_tokens += consumed
                if tr is not None:
                    # one DECODE span per participating slot per chunk —
                    # Perfetto then shows each slot lane's request
                    # interleaving with per-chunk token attribution
                    tr.span("DECODE", t_dec0, t_dec1, tid=1 + slot_id,
                            rid=rid, slot=slot_id, step=self._step_idx,
                            tokens=consumed)
                if self.metrics is not None:
                    self.metrics.inc("serve.tokens_sampled", consumed)
            if slot.remaining <= 0:
                done.append(self._finish(slot_id, t_now))
        self._finish_step(now)
        return done

    def _consume_token(self, slot_id: int, tok: int) -> None:
        """One sampled token into a slot's stream: output append,
        KV/budget bookkeeping, eos retirement — the ONE place decode-
        consumption semantics live. The legacy multi-token chunk loop
        and the ragged step both consume through here, so the two
        serving modes cannot drift."""
        slot = self.slots[slot_id]
        slot.out.append(tok)
        slot.seq_len += 1              # the fed token's KV was written
        slot.remaining -= 1
        self.last_tokens[slot_id] = tok
        if slot.req.eos_id >= 0 and tok == slot.req.eos_id:
            slot.remaining = 0
        self.seq_lens[slot_id] = slot.seq_len
        self.steps_left[slot_id] = slot.remaining

    # --- chunked prefill: the unified ragged step ----------------------------
    def _assign_prefill_chunks(self) -> Dict[int, int]:
        """{slot: chunk tokens} for this step, under the token budget:
        the TOTAL new prefill tokens across slots is capped at
        ``chunk_tokens`` (Sarathi-style budget — decode slots' 1-token
        queries ride along on top), FAIR-SHARED across concurrently
        prefilling slots in admission order (earlier slots take the
        ceil share, and any slot whose remaining prompt is smaller
        frees its share for the rest). A short prompt admitted behind a
        long one therefore rides the SAME steps as the long prompt's
        chunks instead of queueing behind its whole prefill — the
        short-request TTFT protection chunked prefill exists for —
        while a lone prompt still gets the full budget per step."""
        assignments: Dict[int, int] = {}
        budget = self.chunk_tokens
        order = sorted(np.nonzero(self.prefilling)[0],
                       key=lambda s: (self.slots[s].t_admitted, s))
        for i, s in enumerate(order):
            if budget <= 0:
                break
            slot = self.slots[s]
            rem = len(slot.req.prompt) - int(self._prefill_next[s])
            fair = -(-budget // (len(order) - i))      # ceil share
            take = min(budget, fair, rem)
            if take > 0:
                assignments[int(s)] = int(take)
                budget -= take
        return assignments

    def _chunked_step(self, now: float) -> List[Completion]:
        """One token-budget scheduling iteration: pack this step's
        prefill chunks plus every runnable decode slot into ONE
        ``executor.ragged_step`` call, then consume — chunk slots
        advance their prefill cursor (the FINAL chunk's sampled token is
        the request's first output token), decode slots consume exactly
        one token. A long prompt therefore never stalls decode for more
        than one chunk's worth of work."""
        done: List[Completion] = []
        fi = self.fault_injector
        tr = self.tracer
        B = self.num_slots
        runnable = np.logical_and(self.active, ~self.stalled)
        assignments = self._assign_prefill_chunks()
        if not runnable.any() and not assignments:
            if not self.active.any():
                return done            # only restores/queue left
            # every active slot is stalled on an empty pool and no
            # prefill work exists: the legacy preemption ladder applies
            term = self._preempt_for_progress(now)
            if term is not None:
                done.append(term)
            self._grow([s for s in range(self.num_slots)
                        if self.active[s]], 1)
            runnable = np.logical_and(self.active, ~self.stalled)
            if not runnable.any():
                return done
        if fi is not None:
            # injected PREFILL faults fire per chunk slot, before the
            # combined call — per-request isolation exactly as on the
            # legacy prefill path (that one request FAILS, its blocks
            # release, the step's other work proceeds)
            for s in sorted(assignments):
                slot = self.slots[s]
                try:
                    fi.before_prefill(self._step_idx, s, slot.req.rid)
                except Exception as e:
                    req = slot.req
                    t_admit = slot.t_admitted
                    self.tables.release(s)
                    self._clear_slot(s)
                    done.append(self._terminal_queued(
                        req, FAILED, f"executor prefill error: {e}",
                        time.time(), t_admitted=t_admit))
                    del assignments[s]
            if not runnable.any() and not assignments:
                return done
        # speculative drafts: per runnable GREEDY decode slot, look up a
        # prompt-lookup continuation of its history (prompt + out). The
        # draft rides the slot's ragged row as k extra query tokens and
        # COMPETES with prefill chunks for the same per-step token
        # budget — prefill keeps admission-order priority (TTFT), drafts
        # take what is left. k also clips to the slot's granted block
        # coverage (the verify row writes KV through seq_len + k; a
        # partial grow just shortens the draft) and to remaining - 1
        # (a draft can never propose past the token budget).
        drafts: Dict[int, np.ndarray] = {}
        if self.spec:
            budget_left = None
            if self.chunk_tokens:
                budget_left = self.chunk_tokens - sum(assignments.values())
            for s in range(B):
                if not runnable[s]:
                    continue
                slot = self.slots[s]
                if slot.req.temperature != 0.0 or slot.remaining <= 1:
                    continue           # sampled slots ride as plain rows
                k_cap = min(self.draft_len, slot.remaining - 1,
                            int(self._cap_steps[s]) - 1)
                if assignments:
                    # mixed step: the row must fit the chunk bucket
                    k_cap = min(k_cap, self.chunk_tokens - 1)
                if budget_left is not None:
                    k_cap = min(k_cap, budget_left)
                if k_cap < 1:
                    continue
                d = propose_ngram_draft(
                    np.concatenate([np.asarray(slot.req.prompt, np.int64),
                                    np.asarray(slot.out, np.int64)]),
                    k_cap, self.draft_ngram)
                if d.size:
                    drafts[s] = d
                    if budget_left is not None:
                        budget_left -= int(d.size)
        if assignments:
            T_cap = self.chunk_tokens
        elif drafts:
            # ONE speculative bucket (T_cap = 1 + draft_len) regardless
            # of this step's actual k's — no per-k compile buckets
            T_cap = 1 + self.draft_len
        else:
            T_cap = 1
        tokens = np.zeros((B, T_cap), np.int32)
        q_lens = np.zeros(B, np.int32)
        emit = np.zeros(B, bool)
        is_first = np.zeros(B, bool)
        spec_lens = np.zeros(B, np.int32)
        write_pos = self.seq_lens.copy()
        for s in range(B):
            if runnable[s]:
                tokens[s, 0] = self.last_tokens[s]
                q_lens[s] = 1
                emit[s] = True
        for s, d in drafts.items():
            tokens[s, 1:1 + d.size] = d
            q_lens[s] = 1 + d.size
            spec_lens[s] = d.size
        for s, take in assignments.items():
            pos = int(self._prefill_next[s])
            prompt = self.slots[s].req.prompt
            tokens[s, :take] = prompt[pos:pos + take]
            q_lens[s] = take
            emit[s] = pos + take == len(prompt)
            is_first[s] = emit[s]      # final chunk: the FIRST token
            write_pos[s] = self.slots[s].seq_len
        # growth/admission allocations above may have evicted cached
        # blocks — spill their frames before the program writes the pool
        self._flush_spills()
        t0_m = tr.now() if tr is not None else 0.0
        t0_w = time.time()
        try:
            if fi is not None:
                delay = fi.chunk_delay(self._step_idx)
                if delay > 0:
                    time.sleep(delay)
                fi.before_decode(self._step_idx)
            if self.spec:
                nxt, verified, accepts = self.executor.ragged_verify_step(
                    tokens, q_lens, self.tables.table, write_pos, emit,
                    is_first, spec_lens)
                toks = np.asarray(nxt, np.int32).reshape(-1)
                verified = np.asarray(verified, np.int32)
                accepts = np.asarray(accepts, np.int32)
            else:
                toks = np.asarray(self.executor.ragged_step(
                    tokens, q_lens, self.tables.table, write_pos, emit,
                    is_first), np.int32).reshape(-1)
        except Exception as e:
            if tr is not None:
                tr.span("DECODE", t0_m, tr.now(), cat="executor",
                        step=self._step_idx, error=str(e))
            # PER-REQUEST ISOLATION: the combined call failed as a
            # whole, so NO slot consumed tokens. A slot-attributed
            # RequestFault fails exactly that request (decode OR
            # prefill-chunk slot); an unattributed exception fails
            # every slot IN the call — queued and restoring requests
            # keep serving.
            in_call = runnable.copy()
            for s in assignments:
                in_call[s] = True
            done.extend(self._on_decode_error(e, in_call, now))
            return done
        t_now = time.time()
        t1_m = tr.now() if tr is not None else 0.0
        if self.metrics is not None:
            self.metrics.inc("serve.decode_calls")
            self.metrics.inc("serve.ragged_steps")
            self.metrics.observe("serve.decode_chunk_s",
                                 max(0.0, t_now - t0_w))
        # consume prefill chunks: advance cursors, activate final chunks
        for s in sorted(assignments):
            take = assignments[s]
            slot = self.slots[s]
            start = int(self._prefill_next[s])
            pos = start + take
            self._prefill_next[s] = pos
            slot.seq_len = pos         # the chunk's KV is written
            self.seq_lens[s] = pos
            if tr is not None:
                tr.span("PREFILL", t0_m, t1_m, tid=1 + s,
                        rid=slot.req.rid, slot=s, start=start,
                        tokens=take)
            if self.metrics is not None:
                self.metrics.inc("serve.prefill_chunks")
                self.metrics.inc("serve.prefill_chunk_tokens", take)
            self._step_prefill_tokens += take
            if emit[s]:
                # FINAL chunk: its sampled token is the first output
                # token — the slot graduates to decoding (eos /
                # 1-token budgets retire immediately, exactly like the
                # unchunked admission path)
                self.prefilling[s] = False
                done.extend(self._activate_slot(
                    s, slot.req, int(toks[s]), slot.t_admitted))
        # consume decode tokens: one per plain runnable slot; a drafted
        # slot consumes its accepted prefix PLUS the model's bonus token
        # (all byte-identical to the sequential greedy stream), then
        # rolls its over-grown tail blocks back to the pool
        for s in range(B):
            if not runnable[s]:
                continue
            slot = self.slots[s]
            k = int(spec_lens[s]) if self.spec else 0
            if k > 0:
                a = int(accepts[s])
                consumed = 0
                for i in range(a + 1):
                    if slot.remaining <= 0:
                        break          # eos inside the accepted prefix
                    self._consume_token(s, int(verified[s, i]))
                    consumed += 1
                self.spec_rounds += 1
                self.spec_drafted_tokens += k
                self.spec_accepted_tokens += a
                if self.metrics is not None:
                    self.metrics.inc("serve.spec.drafted_tokens", k)
                    self.metrics.inc("serve.spec.accepted_tokens", a)
                    self.metrics.inc("serve.spec.rejected_tokens", k - a)
                    self.metrics.observe("serve.spec.acceptance", a / k)
                # rollback: blocks grown for the verify window beyond
                # the accepted write position return to the pool —
                # fresh tail blocks are private (ref 1, unregistered),
                # so this never touches a shared frame
                self._trim_spec_tail(s)
            else:
                self._consume_token(s, int(toks[s]))
                consumed = 1
                if self.spec:
                    self.spec_plain_rows += 1
            self._step_decode_tokens += consumed
            if tr is not None:
                tr.span("DECODE", t0_m, t1_m, tid=1 + s,
                        rid=slot.req.rid, slot=s, step=self._step_idx,
                        tokens=consumed)
            if self.metrics is not None:
                self.metrics.inc("serve.tokens_sampled", consumed)
            if slot.remaining <= 0:
                done.append(self._finish(s, t_now))
        return done

    def _finish_step(self, now: float) -> None:
        """Common step epilogue: occupancy sample, pool gauges, chaos
        trace mirror + auditor cadence."""
        self._record_occupancy(now)
        m = self.metrics
        if m is not None:
            m.set_gauge("serve.pool_blocks_allocated",
                        self.pool.num_allocated)
            m.set_gauge("serve.pool_blocks_free", self.pool.num_free)
            m.set_gauge("serve.pool_blocks_cached",
                        getattr(self.pool, "num_cached", 0))
            m.set_gauge("serve.active_slots", int(self.active.sum()))
            m.set_gauge("serve.stalled_slots", int(self.stalled.sum()))
            m.set_gauge("serve.prefilling_slots",
                        int(self.prefilling.sum()))
            m.set_gauge("serve.restoring_slots", len(self._restores))
            m.set_gauge("serve.queued", len(self.queue))
            m.set_gauge("serve.live_tokens", int(self.seq_lens.sum()))
            if self.handoff is not None:
                m.set_gauge("serve.disagg.handoff_queue_depth",
                            self.handoff.depth())
        if self.slo is not None:
            # burn-rate/goodput refresh (rate-limited inside the
            # tracker; a clock read per chunk when nothing to do)
            self.slo.tick()
        self._trace_chaos()
        if self.audit_every > 0 and self._step_idx % self.audit_every == 0:
            try:
                self.audit(context=f"step {self._step_idx}")
            except PoolAuditError:
                if self.tracer is not None:
                    self.tracer.instant(
                        "AUDIT_FAIL", cat="audit",
                        violations=list(self.last_audit_violations))
                if m is not None:
                    m.inc("serve.audit_failures")
                raise

    def _on_decode_error(self, e: Exception, runnable: np.ndarray,
                         now: float) -> List[Completion]:
        slot = getattr(e, "slot", None)
        if slot is not None and 0 <= int(slot) < self.num_slots \
                and self.slots[int(slot)].req is not None:
            targets = [int(slot)]
            attributed = True
        else:
            targets = [s for s in range(self.num_slots) if runnable[s]]
            attributed = False
        done: List[Completion] = []
        for s in targets:
            req = self.slots[s].req
            if attributed and self._readmit(s, req):
                continue               # restarted instead of FAILED
            done.append(self._terminal_slot(
                s, FAILED, f"executor decode error: {e}", now,
                register=False))
        return done

    def _readmit(self, slot_id: int, req: Request) -> bool:
        """Opt-in bounded readmission (``readmit_failed`` > 0): restart
        an ATTRIBUTED mid-decode failure from its prompt — the same
        restart-from-prompt mechanics as preemption, so the greedy
        stream is byte-identical on retry success. KV integrity is in
        doubt (executor fault), so nothing registers into the prefix
        cache. Returns True when the request was requeued."""
        if self.readmit_failed <= 0:
            return False
        count = self._readmit_counts.get(req.rid, 0)
        if count >= self.readmit_failed:
            self._readmit_counts.pop(req.rid, None)
            return False
        self._readmit_counts[req.rid] = count + 1
        self.readmissions += 1
        if self.metrics is not None:
            self.metrics.inc("serve.readmissions")
        if self.tracer is not None:
            self.tracer.instant("READMIT", tid=1 + slot_id, slot=slot_id,
                                rid=req.rid, count=count + 1)
        self.tables.release(slot_id)
        self._clear_slot(slot_id)
        if self.tracer is not None:
            self._submit_mono[req.rid] = self.tracer.now()
        self.queue.appendleft(req)     # keeps original submit time
        return True

    # --- invariant auditor ----------------------------------------------------
    def audit(self, context: str = "") -> None:
        """Cross-check pool free lists, refcounts, block tables, the
        prefix-cache index and the scheduler's own slot state; raise
        :class:`~deepspeed_tpu.inference.kv_pool.PoolAuditError` with
        the full violation report on ANY inconsistency. Cheap (O(pool)
        host sets) — the serving default runs it every
        ``audit_every`` chunks; chaos tests run it every chunk."""
        v = self.tables.audit()
        for s in self._restores:
            if self.active[s] or self.stalled[s]:
                v.append(f"slot {s} both restoring and active/stalled")
            if self.slots[s].req is None:
                v.append(f"slot {s} restoring with no bound request")
        if self.host_tier is not None:
            v.extend(f"host tier: {x}" for x in self.host_tier.audit())
        for s in np.nonzero(self.prefilling)[0]:
            if self.slots[s].req is None:
                v.append(f"slot {s} prefilling with no bound request")
                continue
            if self.active[s]:
                v.append(f"slot {s} both prefilling and active")
            if self._prefill_next[s] >= len(self.slots[s].req.prompt):
                v.append(f"slot {s} prefilling past its prompt "
                         f"({int(self._prefill_next[s])})")
        for s, slot in enumerate(self.slots):
            if slot.req is None:
                if self.tables.num_blocks_of(s):
                    v.append(f"free slot {s} still holds blocks "
                             f"{self.tables.blocks_of(s)}")
                if self.active[s] or self.stalled[s] \
                        or self.prefilling[s]:
                    v.append(f"free slot {s} marked "
                             f"active/stalled/prefilling")
            else:
                cap = self.tables.slot_capacity_tokens(s)
                if slot.seq_len > cap:
                    v.append(f"slot {s} seq_len {slot.seq_len} exceeds "
                             f"granted capacity {cap}")
                if self.seq_lens[s] != slot.seq_len:
                    v.append(f"slot {s} seq_len array "
                             f"{int(self.seq_lens[s])} diverges from "
                             f"slot state {slot.seq_len}")
        self.last_audit_violations = v
        if v:
            raise PoolAuditError(v, context)

    # --- stream reclamation ---------------------------------------------------
    def shutdown(self, error: str = "stream closed") -> List[Completion]:
        """Resolve EVERYTHING still in flight or queued to ``CANCELLED``
        and release every block — the reclamation path behind the
        engine's stream leases (an abandoned ``generate_stream`` must
        return its pool to fully-free without waiting for an executor
        invalidation). In-flight prefixes register first, so with a
        caching pool the reclaimed KV parks on the LRU and the next
        session starts warm. Idempotent; audits on exit when auditing
        is enabled."""
        done: List[Completion] = []
        now = time.time()
        for slot_id, slot in enumerate(self.slots):
            if slot.req is not None:
                done.append(self._terminal_slot(
                    slot_id, CANCELLED, error, now))
        while self.queue:
            done.append(self._terminal_queued(
                self.queue.popleft(), CANCELLED, error, now))
        self._cancelled.clear()
        if self.audit_every > 0:
            self.audit(context="shutdown")
        return done

    def run_iter(self, poll_interval: float = 0.001):
        """Drain queue + slots, yielding each Completion as it finishes —
        THE serving loop (wait policy included); ``run()`` and the
        engine's ``generate_stream`` both drive through here so the
        idle/arrival throttling can never diverge between them."""
        while self.busy:
            done = self.step()
            yield from done
            idle = (not self.active.any() and not self.prefilling.any()
                    and not self._restores)
            if idle and self.queue:
                nxt = self.next_arrival()
                if nxt is not None:
                    wait = nxt - time.time()
                    if wait > 0:
                        time.sleep(min(wait, 0.05))
                    continue
                if not done:
                    # pool exhausted with nothing decoding: impossible by
                    # construction (finishing slots free blocks), but do
                    # not spin silently if an executor misbehaves
                    time.sleep(poll_interval)
            elif idle and not self.queue and self.handoff is not None \
                    and not self.handoff.done():
                # decode role waiting on the prefill leg: yield the core
                # instead of hot-stepping — the put lands between sleeps
                time.sleep(poll_interval)

    def run(self, poll_interval: float = 0.001) -> List[Completion]:
        """Drain to completion; all completions in finish order."""
        return list(self.run_iter(poll_interval))

    def prefix_cache_stats(self) -> dict:
        """Prefix-cache effectiveness counters (bench artifact /
        acceptance pins). Block hit-rate is over full prompt blocks
        looked up at admission; token hit-rate is prompt tokens whose
        prefill was skipped over all prompt tokens (the CoW recompute
        token counts as a miss — it IS re-prefilled). ``hit_blocks`` /
        ``block_hit_rate`` stay DEVICE-index hits; host-tier restores
        report separately (``host_*``) but their skipped tokens do fold
        into ``token_hit_rate`` — both tiers skip the same prefill.
        All counters are monotonic over the scheduler's life; eviction
        visibility: ``device_evictions`` (device LRU reclaims — spilled
        when a tier listens, gone otherwise), ``host_spills`` /
        ``host_evictions`` / bytes from the tier itself."""
        lb, hb = self.cache_lookup_blocks, self.cache_hit_blocks
        tt, ht = self.cache_prompt_tokens, self.cache_hit_tokens
        tier = self.host_tier
        ts = tier.stats() if tier is not None else {}
        h_hit, h_miss = ts.get("hits", 0), ts.get("misses", 0)
        return {
            "enabled": self.prefix_cache,
            "lookup_blocks": lb,
            "hit_blocks": hb,
            "block_hit_rate": round(hb / lb, 4) if lb else 0.0,
            "prompt_tokens": tt,
            "hit_tokens": ht,
            "token_hit_rate": round(ht / tt, 4) if tt else 0.0,
            "evictions": getattr(self.pool, "evictions", 0),
            "device_evictions": getattr(self.pool, "evictions", 0),
            "cached_blocks": getattr(self.pool, "num_cached", 0),
            # --- host tier (inference/kv_tiering.py; zeros when off) ---
            "host_tier_enabled": tier is not None,
            "host_spills": ts.get("spills", 0),
            "host_hits": self.host_hit_blocks,
            "host_hit_tokens": self.host_hit_tokens,
            "host_restores": self.host_restores,
            "host_lookup_hit_rate": (round(h_hit / (h_hit + h_miss), 4)
                                     if h_hit + h_miss else 0.0),
            "host_evictions": ts.get("evictions", 0),
            "host_restore_failures": self.host_restore_failures,
            "host_spill_failures": self.host_spill_failures,
            "host_bytes_spilled": ts.get("bytes_spilled", 0),
            "host_bytes_restored": ts.get("bytes_restored", 0),
            "host_bytes_used": ts.get("bytes_used", 0),
            "host_entries": ts.get("entries", 0),
        }

    def disagg_stats(self) -> dict:
        """Disaggregated-serving counters for ONE scheduler's role
        (bench artifact / acceptance pins). A prefill-role scheduler
        moves the ``published_*`` numbers; a decode-role one moves
        ``handoffs``/``restored``/``degrades`` — ``restored +
        degrades`` accounts for every routed-prefill request that
        reached admission. Monotonic over the scheduler's life."""
        return {
            "prefill_role": self.publish_prefixes,
            "decode_role": self.handoff is not None,
            "handoffs": self.disagg_handoffs,
            "restored": self.disagg_restored,
            "degrades": self.disagg_degrades,
            "published_requests": self.published_requests,
            "published_blocks": self.published_blocks,
        }

    def spec_stats(self) -> dict:
        """Speculative-decoding effectiveness counters (the
        ``serve.spec`` registry section / bench artifact).
        ``acceptance_rate`` is accepted over drafted tokens — the
        number to watch: near 0 every verify round paid a 1+K-wide
        pass to emit one token (turn speculation off for that
        traffic); ``mean_accepted_per_round`` + 1 bounds the per-step
        speedup on the drafted rows. ``plain_rows`` counts decode rows
        that ran without a draft (sampled slots, no n-gram match, no
        budget/coverage room) — the bench's engine-vs-recount
        cross-check derives delivered decode tokens as
        ``plain_rows + rounds + accepted`` and must agree with the
        stream byte counts within 5%. Monotonic over the scheduler's
        life."""
        d, a = self.spec_drafted_tokens, self.spec_accepted_tokens
        r = self.spec_rounds
        return {
            "enabled": self.spec,
            "draft_len": self.draft_len,
            "draft_ngram": self.draft_ngram,
            "drafted_tokens": d,
            "accepted_tokens": a,
            "rejected_tokens": d - a,
            "rounds": r,
            "plain_rows": self.spec_plain_rows,
            "acceptance_rate": round(a / d, 4) if d else 0.0,
            "mean_accepted_per_round": round(a / r, 4) if r else 0.0,
        }


def serve_trace(scheduler: ContinuousBatchingScheduler,
                requests: Iterable[Request]) -> List[Completion]:
    """Submit requests (honoring ``arrival_time``) and drain."""
    for r in requests:
        scheduler.submit(r, now=r.arrival_time)
    return scheduler.run()
