"""SLO-driven admission control for the serving path (docs/SERVING.md
"Admission control & self-healing").

The :class:`AdmissionController` is the policy half of the self-healing
control plane: it watches the signals the observability stack already
produces — ``serve.slo.<signal>.burn_rate.<window>s`` gauges from the
:class:`~deepspeed_tpu.observability.slo.SLOTracker`, the scheduler's
queue depth, and KV-pool occupancy — and decides, per admission wave,
whether queued work should be SHED. Shedding is always structured: the
scheduler resolves victims as ``REJECTED`` terminal completions (one
per request, through the same ``_obs_terminal`` path as every other
outcome), never as exceptions, and never touches in-flight slots.

Design points:

- **Hysteresis, not flapping.** Shedding ENTERS when any configured
  signal crosses its ``*_high`` threshold and EXITS only once every
  signal is back under its ``*_low`` threshold. The band between the
  two is sticky — a burn rate oscillating around a single threshold
  cannot toggle the controller every step.
- **Priority classes.** Victims are chosen worst-first: lowest
  ``Request.priority``, then longest prompt (the admission that would
  hold the most KV blocks for the least progress). High-priority short
  prompts are kept.
- **Shed-to-target, not shed-all.** One shed pass trims the queue to
  the low-water target (``queue_depth_low``, or ``keep_fraction`` of
  the queue when no depth band is configured); later passes only trim
  new overflow. The controller degrades service, it does not refuse it.

The controller itself is engine-agnostic: the scheduler calls
``shed()`` at the top of its admit phase, ``ReplicaGroup`` consults
the same object when re-routing around unhealthy replicas, and the
``serve.admission`` metrics section makes every decision auditable.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, fields
from typing import Any, Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class AdmissionConfig:
    """Thresholds for the admission controller. Every band is a
    (high, low) hysteresis pair: shedding starts at ``high``, stops
    only when the signal is back under ``low``. A ``high`` of 0 (or
    0.0) disables that signal entirely."""

    # worst burn rate across all serve.slo.*.burn_rate.* gauges; 1.0
    # means "erring at exactly the budgeted rate" (slo.py)
    burn_rate_high: float = 0.0
    burn_rate_low: float = 0.5
    # scheduler queue length (requests waiting for a slot)
    queue_depth_high: int = 0
    queue_depth_low: int = 0
    # free KV-block fraction: shedding starts when the pool's free
    # fraction drops TO or BELOW pool_free_low, stops once it recovers
    # above pool_free_high (note the inverted sense: low free = bad)
    pool_free_low: float = 0.0
    pool_free_high: float = 0.25
    # while shedding with no queue-depth band configured, keep the
    # best-ranked ceil(len * keep_fraction) queued requests per pass
    keep_fraction: float = 0.5

    def __post_init__(self):
        if not 0.0 < self.keep_fraction <= 1.0:
            raise ValueError(
                f"keep_fraction must be in (0, 1], got {self.keep_fraction}")
        if self.burn_rate_high and self.burn_rate_low > self.burn_rate_high:
            raise ValueError("burn_rate_low must be <= burn_rate_high")
        if self.queue_depth_high and \
                self.queue_depth_low > self.queue_depth_high:
            raise ValueError("queue_depth_low must be <= queue_depth_high")
        if self.pool_free_low and self.pool_free_high < self.pool_free_low:
            raise ValueError("pool_free_high must be >= pool_free_low")

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AdmissionConfig":
        known = {f.name for f in fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown admission config keys: {sorted(unknown)} "
                f"(known: {sorted(known)})")
        return cls(**d)

    @property
    def enabled_signals(self) -> Tuple[str, ...]:
        out = []
        if self.burn_rate_high:
            out.append("burn_rate")
        if self.queue_depth_high:
            out.append("queue_depth")
        if self.pool_free_low:
            out.append("pool_free")
        return tuple(out)


class AdmissionController:
    """Hysteresis-banded load shedder consulted at every admit wave.

    Thread-safety: the shedding flag and episode counters are read by
    the scheduler thread, ``ReplicaGroup`` router threads, and metric
    scrapes concurrently — all mutable state is guarded by ``_lock``.
    """

    def __init__(self, config: Optional[AdmissionConfig] = None, *,
                 metrics=None, slo=None, tracer=None,
                 clock=time.monotonic):
        self.config = config or AdmissionConfig()
        self.metrics = metrics
        self.slo = slo
        self.tracer = tracer
        self._clock = clock
        self._lock = threading.Lock()
        self._shedding = False
        self._reason = ""
        self._episodes = 0
        self._shed_total = 0
        self._admitted_total = 0

    # --- signal evaluation ----------------------------------------------

    def _worst_burn(self) -> float:
        """Worst live burn rate across every signal/window gauge the
        SLOTracker publishes; 0.0 when no SLO is configured."""
        if self.metrics is None:
            return 0.0
        worst = 0.0
        for name, val in self.metrics.gauges().items():
            if name.startswith("serve.slo.") and ".burn_rate." in name:
                worst = max(worst, float(val))
        return worst

    def update(self, *, queue_depth: int = 0,
               pool_free_frac: float = 1.0, storm: bool = False,
               now: Optional[float] = None) -> bool:
        """Re-evaluate the hysteresis state machine; returns the new
        shedding flag. Also the admission-decision SLO tick: burn-rate
        windows decay here even when the engine is otherwise idle."""
        if self.slo is not None:
            self.slo.tick(now)
        cfg = self.config
        burn = self._worst_burn()
        over, under = [], True
        if cfg.burn_rate_high:
            if burn >= cfg.burn_rate_high:
                over.append(f"burn_rate={burn:.2f}")
            if burn >= cfg.burn_rate_low:
                under = False
        if cfg.queue_depth_high:
            if queue_depth >= cfg.queue_depth_high:
                over.append(f"queue_depth={queue_depth}")
            if queue_depth > cfg.queue_depth_low:
                under = False
        if cfg.pool_free_low:
            if pool_free_frac <= cfg.pool_free_low:
                over.append(f"pool_free={pool_free_frac:.2f}")
            if pool_free_frac < cfg.pool_free_high:
                under = False
        if storm:
            over.append("admission_storm")
            under = False
        with self._lock:
            was = self._shedding
            if not was and over:
                self._shedding, self._reason = True, ",".join(over)
                self._episodes += 1
                if self.metrics is not None:
                    self.metrics.inc("serve.admission.shed_episodes")
                if self.tracer is not None:
                    self.tracer.instant("ADMISSION/shed_start",
                                        cat="admission",
                                        reason=self._reason)
            elif was and under:
                self._shedding, self._reason = False, ""
                if self.tracer is not None:
                    self.tracer.instant("ADMISSION/shed_stop",
                                        cat="admission")
            shedding = self._shedding
        if self.metrics is not None:
            self.metrics.set_gauge("serve.admission.shedding",
                                   1.0 if shedding else 0.0)
        return shedding

    # --- victim selection -----------------------------------------------

    def shed(self, requests: Sequence, *, queue_depth: int,
             pool_free_frac: float = 1.0, storm: bool = False,
             now: Optional[float] = None) -> List[Tuple[Any, str]]:
        """One admission wave: re-evaluate the bands, then — while
        shedding — pick the queued victims to resolve ``REJECTED``.
        Returns ``[(request, reason), ...]``; empty while admitting.

        Victims are the worst-ranked overflow past the low-water
        target: rank keeps high ``priority`` first, short prompts
        first, so the shed set is longest-prompt / lowest-priority.
        """
        shedding = self.update(queue_depth=queue_depth,
                               pool_free_frac=pool_free_frac,
                               storm=storm, now=now)
        if not shedding or not requests:
            with self._lock:
                self._admitted_total += len(requests)
            return []
        cfg = self.config
        if cfg.queue_depth_high:
            target = int(cfg.queue_depth_low)
        else:
            target = int(math.ceil(len(requests) * cfg.keep_fraction))
        n_shed = max(0, len(requests) - target)
        if n_shed == 0:
            with self._lock:
                self._admitted_total += len(requests)
            return []
        def _plen(r: Any) -> int:
            p = getattr(r, "prompt", None)
            return 0 if p is None else len(p)

        ranked = sorted(
            requests,
            key=lambda r: (-int(getattr(r, "priority", 0)), _plen(r)))
        victims = ranked[len(requests) - n_shed:]
        with self._lock:
            reason = (f"admission shed ({self._reason})"
                      if self._reason else "admission shed")
            self._shed_total += n_shed
            self._admitted_total += len(requests) - n_shed
        if self.metrics is not None:
            self.metrics.inc("serve.admission.shed", n_shed)
        return [(r, reason) for r in victims]

    # --- introspection ---------------------------------------------------

    @property
    def shedding(self) -> bool:
        with self._lock:
            return self._shedding

    def section(self) -> Dict[str, Any]:
        """``serve.admission`` metrics section (register_collector)."""
        with self._lock:
            return {
                "shedding": self._shedding,
                "reason": self._reason,
                "episodes": self._episodes,
                "shed": self._shed_total,
                "admitted": self._admitted_total,
                "signals": list(self.config.enabled_signals),
            }
