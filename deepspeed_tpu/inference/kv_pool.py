"""Block-pooled KV cache accounting for the serving layer.

The device side of the paged KV cache is a fixed-shape block pool
(``ops/paged_attention.init_paged_pool``) that jitted programs index
through per-slot block tables. THIS module is the host side: which pool
blocks are free, which belong to which serving slot, and the int32 block
tables the programs consume. The logic is pure Python/numpy (the one
import from the device side is the shared ``blocks_for`` rounding rule),
so the continuous-batching scheduler's allocation behavior is
unit-testable without compiling a model
(tests/unit/inference/test_scheduler.py).

Reference analogue: the inference context arena
(csrc/transformer/inference/includes/inference_context.h) sizes ONE
workspace and rotates it; paged blocks instead recycle at sequence
granularity, which is what lets new requests stream into freed capacity
mid-decode (DeepSpeed-Inference arXiv:2207.00032 §serving; Ragged Paged
Attention arXiv:2604.15464).
"""

from typing import List, Sequence

import numpy as np

# ONE rounding rule for host allocation and device sizing — a fork here
# would silently desynchronize the scheduler's accounting from the pool
# shapes the programs index
from deepspeed_tpu.ops.paged_attention import blocks_for  # noqa: F401


class BlockPool:
    """Free-list over ``num_blocks`` pool blocks; block 0 is the NULL
    block (masked writes land there) and is never handed out."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks={num_blocks}: need >= 2 (block 0 is reserved "
                f"as the null block)")
        if block_size < 1:
            raise ValueError(f"block_size={block_size}: must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently freed (still-warm) blocks are reused
        # first
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._allocated = set()

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        """Blocks currently held by slots (occupancy accounting for the
        bench's pool time series; null block excluded)."""
        return len(self._allocated)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> List[int]:
        """Pop ``n`` block ids; raises if the pool cannot satisfy it —
        callers check :meth:`can_allocate` first (queue backpressure is
        the scheduler's job, not an exception path)."""
        if n > len(self._free):
            raise RuntimeError(
                f"block pool exhausted: requested {n}, free {len(self._free)}")
        ids = [self._free.pop() for _ in range(n)]
        self._allocated.update(ids)
        return ids

    def free(self, ids: Sequence[int]) -> None:
        """Return blocks to the pool (sequence finished). Double-free and
        freeing the null block are hard errors — both indicate scheduler
        bookkeeping corruption that would silently cross-contaminate KV."""
        for b in ids:
            if b == 0:
                raise ValueError("cannot free the null block")
            if b not in self._allocated:
                raise ValueError(f"double free of block {b}")
            self._allocated.discard(b)
            self._free.append(b)


class SlotBlockTables:
    """Per-slot block tables: int32 [num_slots, width], unused entries 0.

    The array object is reused in place so the scheduler can hand the
    same backing store to the decode program every step.
    """

    def __init__(self, num_slots: int, width: int, pool: BlockPool):
        self.pool = pool
        self.width = int(width)
        self.table = np.zeros((num_slots, width), np.int32)
        self._slot_blocks: List[List[int]] = [[] for _ in range(num_slots)]

    def capacity_tokens(self) -> int:
        """Max logical positions addressable per slot."""
        return self.width * self.pool.block_size

    def assign(self, slot: int, num_tokens: int) -> None:
        """Allocate and install blocks covering ``num_tokens`` for a slot
        (slot must be empty). Caller checks ``pool.can_allocate`` first."""
        need = blocks_for(num_tokens, self.pool.block_size)
        if need > self.width:
            raise ValueError(
                f"request needs {need} blocks but the block table is "
                f"{self.width} wide ({self.capacity_tokens()} tokens)")
        if self._slot_blocks[slot]:
            raise RuntimeError(f"slot {slot} already holds blocks")
        ids = self.pool.allocate(need)
        self._slot_blocks[slot] = ids
        self.table[slot, :need] = ids
        self.table[slot, need:] = 0

    def grow(self, slot: int, n_blocks: int) -> None:
        """Append ``n_blocks`` fresh pool blocks to an occupied slot's
        table — the ON-DEMAND allocation step (scheduler decode-chunk
        boundaries): pool capacity then tracks live tokens instead of
        the admission-time worst case. Caller checks
        ``pool.can_allocate`` first; growing past the table width is a
        hard error (submit() guarantees total need fits, so an overflow
        here means scheduler accounting corruption)."""
        if n_blocks < 1:
            return
        cur = len(self._slot_blocks[slot])
        if not cur:
            raise RuntimeError(f"slot {slot} holds no blocks — grow() is "
                               f"for occupied slots; use assign()")
        if cur + n_blocks > self.width:
            raise ValueError(
                f"slot {slot}: growing {cur}+{n_blocks} blocks exceeds the "
                f"table width {self.width}")
        ids = self.pool.allocate(n_blocks)
        self._slot_blocks[slot].extend(ids)
        self.table[slot, cur:cur + n_blocks] = ids

    def release(self, slot: int) -> None:
        """Recycle a finished slot's blocks back into the pool."""
        ids = self._slot_blocks[slot]
        if ids:
            self.pool.free(ids)
        self._slot_blocks[slot] = []
        self.table[slot, :] = 0

    def blocks_of(self, slot: int) -> List[int]:
        return list(self._slot_blocks[slot])

    def num_blocks_of(self, slot: int) -> int:
        return len(self._slot_blocks[slot])

    def slot_capacity_tokens(self, slot: int) -> int:
        """Logical positions covered by the slot's CURRENT blocks (the
        on-demand analogue of :meth:`capacity_tokens`, which is the
        table-width bound)."""
        return len(self._slot_blocks[slot]) * self.pool.block_size
