"""Block-pooled KV cache accounting for the serving layer.

The device side of the paged KV cache is a fixed-shape block pool
(``ops/paged_attention.init_paged_pool``) that jitted programs index
through per-slot block tables. THIS module is the host side: which pool
blocks are free, which belong to which serving slot, and the int32 block
tables the programs consume. The logic is pure Python/numpy (the one
import from the device side is the shared ``blocks_for`` rounding rule),
so the continuous-batching scheduler's allocation behavior is
unit-testable without compiling a model
(tests/unit/inference/test_scheduler.py).

Reference analogue: the inference context arena
(csrc/transformer/inference/includes/inference_context.h) sizes ONE
workspace and rotates it; paged blocks instead recycle at sequence
granularity, which is what lets new requests stream into freed capacity
mid-decode (DeepSpeed-Inference arXiv:2207.00032 §serving; Ragged Paged
Attention arXiv:2604.15464).

:class:`PrefixCachingBlockPool` layers PREFIX CACHING on the same pool:
full blocks are content-addressed by a chained hash of their token ids
(:func:`block_content_keys`), held via refcounts so one block can sit in
many slot tables read-only, retained at refcount 0 on an LRU instead of
freed, and reclaimed lazily when the free list runs dry — prompt prefixes
shared across requests (system prompts, few-shot preambles) then prefill
once and serve many (vLLM-style automatic prefix caching over the
DeepSpeed-Inference block pool).
"""

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class PoolAuditError(RuntimeError):
    """Invariant-auditor failure: accounting corruption detected.

    Carries the full violation report — every broken invariant found in
    one sweep, not just the first — so the failure is diagnosable from
    the exception alone (the auditor exists to fail FAST, close to the
    corrupting write, instead of letting a bad refcount surface three
    requests later as silently cross-contaminated KV)."""

    def __init__(self, violations: Sequence[str], context: str = ""):
        self.violations = list(violations)
        head = f"pool audit failed ({len(self.violations)} violation(s)"
        head += f"; {context})" if context else ")"
        super().__init__("\n  - ".join([head] + self.violations))

# ONE rounding rule for host allocation and device sizing — a fork here
# would silently desynchronize the scheduler's accounting from the pool
# shapes the programs index
from deepspeed_tpu.ops.paged_attention import blocks_for  # noqa: F401


def block_content_keys(tokens, block_size: int, salt: int = 0) -> List[bytes]:
    """Content-address keys for each FULL block of a token stream.

    Key i is a chained digest of (key_{i-1}, token ids of block i, salt),
    so equal keys imply equal *prefixes* — the lookup that turns the block
    pool into a prefix cache can walk keys left to right and stop at the
    first miss (vLLM-style hash-chained block identity). Only full blocks
    get keys: a partial block's content is still growing, so it is never
    shareable. ``salt`` namespaces the index (e.g. per model) — two
    streams only collide if tokens AND salt match.
    """
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32).reshape(-1))
    n_full = len(toks) // block_size
    keys: List[bytes] = []
    h = hashlib.sha256(b"prefix-cache-salt:%d" % salt).digest()
    for i in range(n_full):
        m = hashlib.sha256()
        m.update(h)
        m.update(toks[i * block_size:(i + 1) * block_size].tobytes())
        h = m.digest()
        keys.append(h)
    return keys


class BlockPool:
    """Free-list over ``num_blocks`` pool blocks; block 0 is the NULL
    block (masked writes land there) and is never handed out."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks={num_blocks}: need >= 2 (block 0 is reserved "
                f"as the null block)")
        if block_size < 1:
            raise ValueError(f"block_size={block_size}: must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently freed (still-warm) blocks are reused
        # first
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._allocated = set()
        # monotonic high-watermark of blocks held at once (dstprof
        # memory accounting: pool sizing is measured, not guessed)
        self.peak_allocated = 0

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        """Blocks currently held by slots (occupancy accounting for the
        bench's pool time series; null block excluded)."""
        return len(self._allocated)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> List[int]:
        """Pop ``n`` block ids; raises if the pool cannot satisfy it —
        callers check :meth:`can_allocate` first (queue backpressure is
        the scheduler's job, not an exception path)."""
        if n > len(self._free):
            raise RuntimeError(
                f"block pool exhausted: requested {n}, free {len(self._free)}")
        ids = [self._free.pop() for _ in range(n)]
        self._allocated.update(ids)
        self.peak_allocated = max(self.peak_allocated, len(self._allocated))
        return ids

    def free(self, ids: Sequence[int]) -> None:
        """Return blocks to the pool (sequence finished). Double-free and
        freeing the null block are hard errors — both indicate scheduler
        bookkeeping corruption that would silently cross-contaminate KV."""
        for b in ids:
            if b == 0:
                raise ValueError("cannot free the null block")
            if b not in self._allocated:
                raise ValueError(f"double free of block {b}")
            self._allocated.discard(b)
            self._free.append(b)

    def release_blocks(self, ids: Sequence[int]) -> None:
        """Policy seam for :class:`SlotBlockTables`: a slot dropping its
        blocks. Plain pools free them outright; the prefix-caching pool
        overrides this with refcount decrements so shared/cached blocks
        survive the releasing slot."""
        self.free(ids)

    def audit(self) -> List[str]:
        """Cheap host-side invariant sweep; returns violations (empty =
        clean). O(num_blocks) sets/sums — safe to run every serving
        chunk. The scheduler's auditor layers table cross-checks on top
        (:meth:`SlotBlockTables.audit`)."""
        v: List[str] = []
        free = self._free
        free_set = set(free)
        if len(free_set) != len(free):
            v.append(f"free list holds duplicates "
                     f"({len(free) - len(free_set)})")
        if 0 in free_set or 0 in self._allocated:
            v.append("null block 0 on the free list or allocated")
        bad = [b for b in free_set | self._allocated
               if not (0 < b < self.num_blocks)]
        if bad:
            v.append(f"out-of-range block ids {sorted(bad)[:8]}")
        overlap = free_set & self._allocated
        if overlap:
            v.append(f"blocks both free and allocated "
                     f"{sorted(overlap)[:8]}")
        if len(free_set) + len(self._allocated) != self.num_blocks - 1:
            v.append(
                f"accounting leak: free {len(free_set)} + allocated "
                f"{len(self._allocated)} != usable {self.num_blocks - 1}")
        return v


class PrefixCachingBlockPool(BlockPool):
    """Block pool with a content-addressed prefix-cache index on top.

    Three disjoint states per block (null block 0 is in none of them):

    - FREE: on the free list, content meaningless.
    - HELD: refcount >= 1 — referenced by that many slot tables. A held
      block may ALSO be registered in the index (its content is a known
      token-block), in which case new admissions can share it (refcount
      goes up) while the writer is still decoding.
    - CACHED: refcount 0 but registered — content (and the device KV
      behind it) still valid; sits on an LRU and is reclaimed only when
      the free list runs dry, so the cache is strictly opportunistic:
      ``can_allocate``/``num_free`` count cached blocks as allocatable
      capacity and admission/growth backpressure can never deadlock on
      cache residency.

    Invariants (hard errors, pinned in
    tests/unit/inference/test_prefix_cache.py): refcounts never go
    negative, a referenced block is never evicted, the null block is
    never indexed or evicted, and a registered block's key can never be
    silently rebound.
    """

    def __init__(self, num_blocks: int, block_size: int, salt: int = 0):
        super().__init__(num_blocks, block_size)
        self.salt = int(salt)
        self._refs: Dict[int, int] = {}
        self._index: Dict[bytes, int] = {}          # content key -> block
        self._block_key: Dict[int, bytes] = {}      # reverse mapping
        # zero-ref cached blocks, least-recently released first
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self.evictions = 0
        # TIERED KV (inference/kv_tiering.py): the eviction hook. When
        # set, every _evict reports (content key, block id) BEFORE the
        # frame can be rewritten — the scheduler queues the pair and
        # flushes a device→host spill ahead of the next executor write,
        # so "evicted" stops meaning "gone" and starts meaning
        # "demoted to the host tier". Pure notification: the pool's own
        # accounting (and its never-add-backpressure contract) is
        # unchanged whether or not anyone listens.
        self.spill_sink = None

    # --- capacity: cached blocks are allocatable --------------------------
    @property
    def num_free(self) -> int:
        """Allocatable blocks: truly free + evictable (cached, ref 0).
        This is the number growth/admission may claim right now — cache
        residency must never read as pool pressure."""
        return len(self._free) + len(self._lru)

    @property
    def num_cached(self) -> int:
        """Zero-ref blocks retained only for prefix reuse."""
        return len(self._lru)

    def can_allocate(self, n: int) -> bool:
        return n <= self.num_free

    def refcount(self, bid: int) -> int:
        return self._refs.get(bid, 0)

    def is_cached(self, bid: int) -> bool:
        return bid in self._block_key

    # --- allocation / refcounting -----------------------------------------
    def _evict(self, bid: int) -> None:
        """Drop a CACHED block from the index so its frame can be
        reallocated. Internal to :meth:`allocate` (LRU order); evicting a
        referenced block or the null block indicates corrupted
        accounting and is a hard error, never a silent KV clobber."""
        if bid == 0:
            raise ValueError("cannot evict the null block")
        if self._refs.get(bid, 0):
            raise RuntimeError(
                f"evicting block {bid} with refcount {self._refs[bid]} — "
                f"a shared block's KV would be clobbered")
        key = self._block_key.pop(bid, None)
        if key is None:
            raise RuntimeError(f"block {bid} is not cached")
        del self._index[key]
        self._lru.pop(bid, None)
        self.evictions += 1
        if self.spill_sink is not None:
            self.spill_sink(key, bid)

    def allocate(self, n: int) -> List[int]:
        """Pop ``n`` frames: free list first, then LRU eviction of cached
        blocks. Allocated blocks start with refcount 1 (owned by the
        claiming slot)."""
        if n > self.num_free:
            raise RuntimeError(
                f"block pool exhausted: requested {n}, free "
                f"{len(self._free)} + cached {len(self._lru)}")
        ids = []
        for _ in range(n):
            if self._free:
                ids.append(self._free.pop())
            else:
                bid, _ = self._lru.popitem(last=False)   # oldest first
                self._evict(bid)
                ids.append(bid)
        self._allocated.update(ids)
        self.peak_allocated = max(self.peak_allocated, len(self._allocated))
        for b in ids:
            self._refs[b] = 1
        return ids

    def share(self, bid: int) -> None:
        """Add a table reference to an existing block (cache hit reuse).
        A CACHED block leaves the LRU — it is pinned until released."""
        if bid == 0:
            raise ValueError("cannot share the null block")
        r = self._refs.get(bid, 0)
        if r == 0:
            if bid not in self._block_key:
                raise ValueError(
                    f"cannot share block {bid}: neither held nor cached")
            self._lru.pop(bid, None)
            self._allocated.add(bid)
            self.peak_allocated = max(self.peak_allocated,
                                      len(self._allocated))
        self._refs[bid] = r + 1

    def release_blocks(self, ids: Sequence[int]) -> None:
        """Drop one table reference per block. At refcount 0 a registered
        block parks on the cache LRU (KV intact, evictable); an
        unregistered one frees outright. Going below zero is a hard
        error — it means two owners both thought the ref was theirs."""
        for b in ids:
            if b == 0:
                raise ValueError("cannot release the null block")
            r = self._refs.get(b, 0)
            if r <= 0:
                raise ValueError(
                    f"refcount underflow: block {b} released at ref {r}")
            r -= 1
            self._refs[b] = r
            if r == 0:
                self._allocated.discard(b)
                if b in self._block_key:
                    self._lru[b] = None              # most recent at end
                else:
                    self._free.append(b)

    def free(self, ids: Sequence[int]) -> None:
        raise RuntimeError(
            "PrefixCachingBlockPool blocks are refcounted — use "
            "release_blocks(); free() would bypass sharing/cache state")

    # --- content index ----------------------------------------------------
    def register(self, key: bytes, bid: int) -> bool:
        """Publish a held block's content key. Returns False (no-op) when
        the key is already indexed — first writer wins, duplicates just
        free normally on release (dedup without a device copy). The
        registering slot must still hold the block (ref >= 1): a
        zero-ref or free frame has no owner vouching for its content."""
        if bid == 0:
            raise ValueError("cannot register the null block")
        if self._refs.get(bid, 0) < 1:
            raise ValueError(
                f"cannot register block {bid}: refcount is 0 — only a "
                f"holder may publish content")
        if key in self._index:
            return False
        prev = self._block_key.get(bid)
        if prev is not None and prev != key:
            raise ValueError(
                f"block {bid} already registered under a different key — "
                f"content changed while indexed")
        self._index[key] = bid
        self._block_key[bid] = key
        return True

    def lookup(self, keys: Sequence[bytes]) -> List[int]:
        """Longest indexed prefix of ``keys`` → block ids. Pure peek: no
        refcount or LRU mutation (callers pin matches via :meth:`share`
        before anything can evict them)."""
        out = []
        for k in keys:
            bid = self._index.get(k)
            if bid is None:
                break
            out.append(bid)
        return out

    def audit(self) -> List[str]:
        """Prefix-caching invariant sweep: the three block states (FREE /
        HELD / CACHED) must partition the usable pool, refcounts must
        agree with the held set, and the content index must be a
        bijection whose entries are all live frames."""
        v: List[str] = []
        free_set = set(self._free)
        lru_set = set(self._lru)
        held = {b for b, r in self._refs.items() if r > 0}
        if len(free_set) != len(self._free):
            v.append(f"free list holds duplicates "
                     f"({len(self._free) - len(free_set)})")
        if 0 in free_set | lru_set | held:
            v.append("null block 0 in free/cached/held state")
        bad = [b for b in free_set | lru_set | held
               if not (0 < b < self.num_blocks)]
        if bad:
            v.append(f"out-of-range block ids {sorted(bad)[:8]}")
        neg = {b: r for b, r in self._refs.items() if r < 0}
        if neg:
            v.append(f"negative refcounts {neg}")
        for name, other in (("cached", lru_set), ("held", held)):
            overlap = free_set & other
            if overlap:
                v.append(f"blocks both free and {name} "
                         f"{sorted(overlap)[:8]}")
        overlap = lru_set & held
        if overlap:
            v.append(f"blocks both cached (ref 0) and held "
                     f"{sorted(overlap)[:8]}")
        if held != self._allocated:
            v.append(f"allocated set disagrees with refcounts: "
                     f"allocated-only "
                     f"{sorted(self._allocated - held)[:8]}, held-only "
                     f"{sorted(held - self._allocated)[:8]}")
        if len(free_set) + len(lru_set) + len(held) != self.num_blocks - 1:
            v.append(
                f"accounting leak: free {len(free_set)} + cached "
                f"{len(lru_set)} + held {len(held)} != usable "
                f"{self.num_blocks - 1}")
        # content index <-> reverse map bijection, entries live
        for key, bid in self._index.items():
            if self._block_key.get(bid) != key:
                v.append(f"index entry block {bid} not mirrored in "
                         f"reverse map")
        for bid, key in self._block_key.items():
            if self._index.get(key) != bid:
                v.append(f"reverse-map block {bid} not mirrored in index")
            if bid in free_set:
                v.append(f"indexed block {bid} sits on the free list")
        for bid in lru_set:
            if bid not in self._block_key:
                v.append(f"LRU block {bid} has no content key")
            if self._refs.get(bid, 0) != 0:
                v.append(f"LRU block {bid} has refcount "
                         f"{self._refs.get(bid)}")
        return v


class SlotBlockTables:
    """Per-slot block tables: int32 [num_slots, width], unused entries 0.

    The array object is reused in place so the scheduler can hand the
    same backing store to the decode program every step.
    """

    def __init__(self, num_slots: int, width: int, pool: BlockPool):
        self.pool = pool
        self.width = int(width)
        self.table = np.zeros((num_slots, width), np.int32)
        self._slot_blocks: List[List[int]] = [[] for _ in range(num_slots)]

    def capacity_tokens(self) -> int:
        """Max logical positions addressable per slot."""
        return self.width * self.pool.block_size

    def assign(self, slot: int, num_tokens: int) -> None:
        """Allocate and install blocks covering ``num_tokens`` for a slot
        (slot must be empty). Caller checks ``pool.can_allocate`` first."""
        need = blocks_for(num_tokens, self.pool.block_size)
        if need > self.width:
            raise ValueError(
                f"request needs {need} blocks but the block table is "
                f"{self.width} wide ({self.capacity_tokens()} tokens)")
        if self._slot_blocks[slot]:
            raise RuntimeError(f"slot {slot} already holds blocks")
        ids = self.pool.allocate(need)
        self._slot_blocks[slot] = ids
        self.table[slot, :need] = ids
        self.table[slot, need:] = 0

    def grow(self, slot: int, n_blocks: int) -> None:
        """Append ``n_blocks`` fresh pool blocks to an occupied slot's
        table — the ON-DEMAND allocation step (scheduler decode-chunk
        boundaries): pool capacity then tracks live tokens instead of
        the admission-time worst case. Caller checks
        ``pool.can_allocate`` first; growing past the table width is a
        hard error (submit() guarantees total need fits, so an overflow
        here means scheduler accounting corruption)."""
        if n_blocks < 1:
            return
        cur = len(self._slot_blocks[slot])
        if not cur:
            raise RuntimeError(f"slot {slot} holds no blocks — grow() is "
                               f"for occupied slots; use assign()")
        if cur + n_blocks > self.width:
            raise ValueError(
                f"slot {slot}: growing {cur}+{n_blocks} blocks exceeds the "
                f"table width {self.width}")
        ids = self.pool.allocate(n_blocks)
        self._slot_blocks[slot].extend(ids)
        self.table[slot, cur:cur + n_blocks] = ids

    def assign_cached(self, slot: int, shared_ids: Sequence[int],
                      num_tokens: int, cow_src: Optional[int] = None
                      ) -> Optional[List[Tuple[int, int]]]:
        """Install a cached-prefix admission: ``shared_ids`` (an indexed
        block-aligned prefix, used READ-ONLY) followed by fresh blocks
        covering the rest of ``num_tokens``. Requires a
        :class:`PrefixCachingBlockPool`.

        ``cow_src`` is the copy-on-write case — the prompt is entirely
        covered by cached blocks, so the last prompt token must be
        recomputed (its logits seed sampling) and would land INSIDE the
        final cached block: that block is not shared; instead the first
        fresh block becomes its copy target and the returned ``(src,
        dst)`` pair tells the executor to duplicate the device KV before
        the slot writes. The shared original is never mutated.

        Returns the copy pairs (possibly empty), or None — with NO state
        change — when the pool cannot supply the fresh tail
        (backpressure; the cached prefix is re-released). Callers must
        apply the device copies before the next pool allocation: the
        source keeps no reference once this returns, so a later
        allocation could evict it.
        """
        need = blocks_for(num_tokens, self.pool.block_size)
        if need > self.width:
            raise ValueError(
                f"request needs {need} blocks but the block table is "
                f"{self.width} wide ({self.capacity_tokens()} tokens)")
        if self._slot_blocks[slot]:
            raise RuntimeError(f"slot {slot} already holds blocks")
        shared_ids = list(shared_ids)
        # pin everything we read — including the CoW source, which must
        # survive until the device copy — before any allocation can evict
        pins = shared_ids + ([cow_src] if cow_src is not None else [])
        for b in pins:
            self.pool.share(b)
        n_fresh = need - len(shared_ids)
        if not self.pool.can_allocate(n_fresh):
            self.pool.release_blocks(pins)
            return None
        fresh = self.pool.allocate(n_fresh)
        pairs: List[Tuple[int, int]] = []
        if cow_src is not None:
            pairs.append((cow_src, fresh[0]))
            # the pin outlives this call only on the LRU (src stays
            # indexed); safe because the copy happens before the caller
            # allocates again
            self.pool.release_blocks([cow_src])
        ids = shared_ids + fresh
        self._slot_blocks[slot] = ids
        self.table[slot, :need] = ids
        self.table[slot, need:] = 0
        return pairs

    def trim(self, slot: int, keep_blocks: int) -> int:
        """Release the slot's TAIL blocks past ``keep_blocks`` — the
        speculative-decoding rollback: a rejected draft leaves the
        blocks grown for its verify window past the accepted write
        position, and under pool pressure they must not sit idle on a
        slot that no longer covers them. Pure reference bookkeeping
        (``release_blocks``, newest-first like :meth:`release`): a
        block another slot or the prefix cache still references just
        drops THIS slot's reference — no frame is ever rewritten.
        Returns the number of blocks released (0 when ``keep_blocks``
        already covers the slot)."""
        ids = self._slot_blocks[slot]
        keep_blocks = max(int(keep_blocks), 0)
        if keep_blocks >= len(ids):
            return 0
        tail = ids[keep_blocks:]
        self.pool.release_blocks(tail[::-1])
        del ids[keep_blocks:]
        self.table[slot, keep_blocks:] = 0
        return len(tail)

    def release(self, slot: int) -> None:
        """Recycle a finished slot's blocks back into the pool (with a
        prefix-caching pool: drop this slot's references — shared/cached
        blocks survive). Released TAIL-FIRST: the caching pool's LRU
        appends in release order and evicts oldest-first, so a
        sequence's tail blocks are reclaimed before its head — a prefix
        truncated at the tail still matches partially, one missing its
        head matches nothing (lookup walks keys left to right)."""
        ids = self._slot_blocks[slot]
        if ids:
            self.pool.release_blocks(ids[::-1])
        self._slot_blocks[slot] = []
        self.table[slot, :] = 0

    def blocks_of(self, slot: int) -> List[int]:
        return list(self._slot_blocks[slot])

    def num_blocks_of(self, slot: int) -> int:
        return len(self._slot_blocks[slot])

    def slot_capacity_tokens(self, slot: int) -> int:
        """Logical positions covered by the slot's CURRENT blocks (the
        on-demand analogue of :meth:`capacity_tokens`, which is the
        table-width bound)."""
        return len(self._slot_blocks[slot]) * self.pool.block_size

    def audit(self) -> List[str]:
        """Pool sweep + table cross-checks: every table row mirrors its
        slot's block list, every held block is reachable from exactly
        as many tables as its refcount says (prefix-caching pool) or
        exactly one (plain pool), and no free/cached frame is still
        wired into a table. This is the serving auditor's core — it
        catches the leak/double-free/aliasing class at the step
        boundary where it happened."""
        v = self.pool.audit()
        refcounted = isinstance(self.pool, PrefixCachingBlockPool)
        table_refs: Dict[int, int] = {}
        for slot, ids in enumerate(self._slot_blocks):
            n = len(ids)
            if n > self.width:
                v.append(f"slot {slot} holds {n} blocks > width "
                         f"{self.width}")
                n = self.width
            row = self.table[slot]
            if list(row[:n]) != list(ids[:n]):
                v.append(f"slot {slot} table row diverges from its "
                         f"block list: {row[:n].tolist()} vs {ids[:n]}")
            if n < self.width and row[n:].any():
                v.append(f"slot {slot} table has stale entries past its "
                         f"{n} blocks: {row[n:].tolist()}")
            if len(set(ids)) != len(ids):
                v.append(f"slot {slot} references a block twice: {ids}")
            for b in ids:
                if b == 0:
                    v.append(f"slot {slot} references the null block")
                else:
                    table_refs[b] = table_refs.get(b, 0) + 1
        if refcounted:
            for b, n in table_refs.items():
                r = self.pool.refcount(b)
                if r != n:
                    v.append(f"block {b}: refcount {r} but referenced "
                             f"by {n} table(s)")
            stranded = self.pool._allocated - set(table_refs)
            if stranded:
                v.append(f"held blocks in no table (leaked refs) "
                         f"{sorted(stranded)[:8]}")
        else:
            multi = {b: n for b, n in table_refs.items() if n > 1}
            if multi:
                v.append(f"plain-pool blocks shared across slots "
                         f"{multi}")
            if set(table_refs) != self.pool._allocated:
                v.append(
                    f"table blocks disagree with the allocated set: "
                    f"tables-only "
                    f"{sorted(set(table_refs) - self.pool._allocated)[:8]}"
                    f", allocated-only "
                    f"{sorted(self.pool._allocated - set(table_refs))[:8]}")
        return v
