"""Collective-call logging (reference ``deepspeed/utils/comms_logging.py``).

Inside ``jit`` a collective has no host-visible wall time, so the logger
records two kinds of events: trace-time records (op name, payload bytes, axis)
whenever a verb is traced, and eager wall-time records when verbs run outside
jit. ``log_summary()`` aggregates like the reference (comm.py:409).
"""

import math
from typing import Dict, List, Optional

from deepspeed_tpu.comm.collective_cost import (
    payload_bytes_from_shape, wire_bytes,
)
from deepspeed_tpu.utils.logging import logger


def convert_size(size_bytes: float) -> str:
    if size_bytes == 0:
        return "0B"
    names = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    return f"{round(size_bytes / p, 2)} {names[i]}"


def get_msg_size_from_shape(shape, dtype) -> int:
    """Payload bytes of one array — shared dtype-size × element-count
    arithmetic (comm/collective_cost.py), the same table the dstlint
    SPMD pass prices static traces with."""
    return payload_bytes_from_shape(shape, dtype)


class CommsLogger:
    def __init__(self, enabled: bool = False, verbose: bool = False,
                 prof_all: bool = True, debug: bool = False, prof_ops: List[str] = None):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.debug = debug
        self.prof_ops = prof_ops or []
        # op name -> msg size -> [count, total_latency_ms, total_payload
        # bytes, total_wire_bytes, timed_count] (wire = per-device
        # interconnect bytes per the shared collective_cost table; 0
        # when the op kind or group size was unknown at record time;
        # timed_count counts only samples with a REAL measured latency —
        # trace-time records are untimed and must not average fabricated
        # zeros into the latency stats)
        self.comms_dict: Dict[str, Dict[int, List[float]]] = {}

    def configure(self, comms_config) -> None:
        self.enabled = comms_config.enabled
        self.verbose = comms_config.verbose
        self.prof_all = comms_config.prof_all
        self.debug = comms_config.debug
        self.prof_ops = list(comms_config.prof_ops)

    def should_profile(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, op_name: str, latency_ms: Optional[float],
               msg_size: int, kind: Optional[str] = None,
               group_size: Optional[int] = None) -> None:
        """Record one collective. ``kind``/``group_size`` (when the verb
        knows them) price the per-device wire bytes via the shared
        :func:`collective_cost.wire_bytes` table — the SAME arithmetic
        the dstlint SPMD pass applies to static traces, so runtime and
        static accounting cannot disagree.

        ``latency_ms=None`` marks an UNTIMED sample — a trace-time
        record (inside jit a collective has no host wall time). Untimed
        samples count calls and bytes but are excluded from the latency
        average, so :meth:`log_summary` never dilutes real measurements
        with fabricated zeros."""
        if op_name not in self.comms_dict:
            self.comms_dict[op_name] = {}
        sizes = self.comms_dict[op_name]
        if msg_size not in sizes:
            sizes[msg_size] = [0, 0.0, 0.0, 0.0, 0]
        rec = sizes[msg_size]
        rec[0] += 1
        if latency_ms is not None:
            rec[1] += latency_ms
            rec[4] += 1
        rec[2] += msg_size
        if kind is not None and group_size is not None:
            rec[3] += wire_bytes(kind, msg_size, group_size)
        if self.verbose:
            shown = ("traced" if latency_ms is None
                     else f"{latency_ms:.2f}")
            logger.info(
                f"comm op: {op_name} | time (ms): {shown} | "
                f"msg size: {convert_size(msg_size)}"
            )

    def wire_totals(self) -> Dict[str, Dict[str, float]]:
        """{op: {count, payload_bytes, wire_bytes}} aggregated over all
        message sizes — the runtime half of the static/runtime byte
        cross-check (tests/unit/test_comm.py)."""
        out: Dict[str, Dict[str, float]] = {}
        for op, sizes in self.comms_dict.items():
            tot = {"count": 0, "payload_bytes": 0.0, "wire_bytes": 0.0}
            for rec in sizes.values():
                tot["count"] += rec[0]
                tot["payload_bytes"] += rec[2]
                tot["wire_bytes"] += rec[3]
            out[op] = tot
        return out

    def registry_section(self) -> Dict[str, float]:
        """Flat ``wire_totals()`` view for a dstrace ``MetricsRegistry``
        collector (``engine.metrics.snapshot()["comm"]``): per-op count
        / payload / wire bytes plus all-op totals, priced by the SAME
        ``collective_cost`` table the dstlint SPMD pass budgets with —
        one arithmetic, three consumers (static lint, runtime log,
        metrics snapshot), zero drift."""
        out: Dict[str, float] = {"enabled": float(self.enabled)}
        total_payload = total_wire = total_count = 0.0
        for op, tot in self.wire_totals().items():
            out[f"{op}.count"] = tot["count"]
            out[f"{op}.payload_bytes"] = tot["payload_bytes"]
            out[f"{op}.wire_bytes"] = tot["wire_bytes"]
            total_count += tot["count"]
            total_payload += tot["payload_bytes"]
            total_wire += tot["wire_bytes"]
        out["total.count"] = total_count
        out["total.payload_bytes"] = total_payload
        out["total.wire_bytes"] = total_wire
        return out

    def log_summary(self) -> str:
        lines = [f"{'Op':<24}{'Message Size':<16}{'Count':<8}"
                 f"{'Timed':<7}{'Total Latency(ms)':<20}"
                 f"{'Avg Latency(ms)':<18}{'Wire Bytes':<14}"]
        for op, sizes in sorted(self.comms_dict.items()):
            for msg_size, rec in sorted(sizes.items()):
                count, total_ms, wire = rec[0], rec[1], rec[3]
                timed = rec[4] if len(rec) > 4 else count
                # average over TIMED samples only — trace-time records
                # carry no wall time and must not drag the average to 0
                avg = f"{total_ms / timed:.3f}" if timed else "-"
                lines.append(
                    f"{op:<24}{convert_size(msg_size):<16}{count:<8}"
                    f"{timed:<7}{total_ms:<20.2f}{avg:<18}"
                    f"{convert_size(wire):<14}"
                )
        summary = "\n".join(lines)
        logger.info("\n" + summary)
        return summary
