"""Communication verbs over XLA collectives.

TPU-native analogue of ``deepspeed/comm/comm.py`` (:215-627): the same
torch.distributed-shaped API, implemented two ways:

1. **Axis verbs** — used inside ``shard_map``/``jit``: thin wrappers over
   ``jax.lax`` collectives keyed by mesh-axis name. "Process groups" are mesh
   axes; a group tuple like ``("data", "sequence")`` reduces over both.
2. **Host init** — ``init_distributed()`` performs the multi-host rendezvous
   via ``jax.distributed.initialize`` (the analogue of
   ``torch.distributed.init_process_group`` NCCL rendezvous, comm/comm.py:562),
   driven by the same env conventions the launcher writes.

Every verb is wrapped in ``timed_op``-style profiling feeding the comms
logger (reference comm.py:104-145). Inside jit only payload metadata is
recorded (collectives have no host wall-time under jit); eager calls record
wall time.

Reduction semantics note: like NCCL, ``all_reduce(op=AVG)`` divides by the
group size; XLA's ``psum`` is the SUM primitive and others derive from it.
"""

import os
import time
from enum import Enum
from typing import Optional, Sequence, Union

import jax
from deepspeed_tpu.utils.jax_compat import shard_map, axis_size
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.comms_logging import CommsLogger, get_msg_size_from_shape
from deepspeed_tpu.utils.logging import logger

AxisName = Union[str, Sequence[str]]


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4
    BAND = 5
    BOR = 6
    BXOR = 7
    UNUSED = 8


comms_logger = CommsLogger()

_INITIALIZED = False
_COMM_BACKEND_NAME = "xla-ici"

# dstfleet measured-collective sink: a MetricsRegistry that eager verbs
# record real per-verb latency histograms (`comm.<verb>.latency_s`) and
# measured wire-byte counters (`comm.<verb>.bytes`, priced by the SAME
# collective_cost table the static SPMD budgets use) into. Engines
# register their registry at init (last registration wins — one process
# normally drives one engine's collectives; multi-engine processes can
# re-point it around a call). None = registry recording off.
_metrics_registry = None


def set_metrics_registry(registry) -> None:
    """Point measured-collective recording at ``registry`` (a dstrace
    ``MetricsRegistry``; None disconnects)."""
    global _metrics_registry
    _metrics_registry = registry


def get_metrics_registry():
    return _metrics_registry


def _record_measured(verb: str, latency_s: float, payload_bytes: int,
                     kind: Optional[str], group_size: Optional[int],
                     op_label: Optional[str] = None) -> None:
    """One MEASURED collective: a host-boundary call whose wall time is
    real (eager helpers, barriers — anything bracketed by
    ``block_until_ready``). Lands in the comms logger as a TIMED sample
    and in the registered metrics registry as latency histogram + byte
    counters. In-graph collectives never reach here — their latency has
    no host-visible wall time and is accounted as the per-step envelope
    (``train.comm_fraction``) instead."""
    from deepspeed_tpu.comm.collective_cost import wire_bytes

    if comms_logger.should_profile(verb):
        comms_logger.append(op_label or verb, latency_s * 1e3,
                            payload_bytes, kind=kind,
                            group_size=group_size)
    reg = _metrics_registry
    if reg is None:
        return
    reg.observe(f"comm.{verb}.latency_s", latency_s)
    reg.inc(f"comm.{verb}.count")
    if payload_bytes:
        reg.inc(f"comm.{verb}.payload_bytes", payload_bytes)
        if kind is not None and group_size:
            reg.inc(f"comm.{verb}.bytes",
                    wire_bytes(kind, payload_bytes, group_size))


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(dist_backend: str = "xla-ici",
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method: Optional[str] = None,
                     dist_init_required: Optional[bool] = None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Multi-host rendezvous (reference comm/comm.py:562 ``init_distributed``).

    Single-process → no-op beyond marking initialized. Multi-host (launcher
    sets DS_TPU_COORDINATOR or JAX_COORDINATOR_ADDRESS env, or OMPI vars are
    discovered like reference comm.py:627) → ``jax.distributed.initialize``.
    """
    global _INITIALIZED, _COMM_BACKEND_NAME
    if _INITIALIZED:
        return
    _COMM_BACKEND_NAME = dist_backend

    coordinator = (init_method
                   or os.environ.get("DS_TPU_COORDINATOR")
                   or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coordinator is None and auto_mpi_discovery and "OMPI_COMM_WORLD_SIZE" in os.environ:
        # MPI-launched: discover rank/world from OMPI env (reference comm.py:627)
        world_size = int(os.environ["OMPI_COMM_WORLD_SIZE"])
        rank = int(os.environ["OMPI_COMM_WORLD_RANK"])
        coordinator = f"{os.environ.get('MASTER_ADDR', 'localhost')}:{distributed_port}"
    if coordinator is None and "TPU_WORKER_HOSTNAMES" in os.environ:
        # TPU pod metadata (the cloud-environment analogue of the
        # reference's AzureML/SageMaker env patching, comm.py:682,714):
        # GCE TPU VMs export the worker list + this worker's index
        hosts = [h.strip() for h in
                 os.environ["TPU_WORKER_HOSTNAMES"].split(",") if h.strip()]
        if len(hosts) > 1:
            coordinator = f"{hosts[0]}:{distributed_port}"
            world_size = len(hosts)
            # -1 = unset: jax.distributed.initialize then infers the rank
            # itself (defaulting to 0 would make every host claim rank 0)
            rank = int(os.environ.get("TPU_WORKER_ID",
                                      os.environ.get("CLOUD_TPU_TASK_ID",
                                                     -1)))
    # the dst launcher's rendezvous contract (launcher/runner.py:148-150)
    if coordinator is not None:
        if world_size <= 0 and "DS_TPU_NUM_PROCESSES" in os.environ:
            world_size = int(os.environ["DS_TPU_NUM_PROCESSES"])
        if rank < 0 and "DS_TPU_PROCESS_ID" in os.environ:
            rank = int(os.environ["DS_TPU_PROCESS_ID"])
    if coordinator is not None and world_size != 1:
        kwargs = {}
        if rank >= 0:
            kwargs["process_id"] = rank
        if world_size > 0:
            kwargs["num_processes"] = world_size
        # NOTE: must not touch jax.default_backend()/devices here —
        # distributed.initialize requires an uninitialized XLA backend
        plat = (os.environ.get("JAX_PLATFORMS")
                or str(getattr(jax.config, "jax_platforms", None) or ""))
        if plat.startswith("cpu"):
            # multi-process CPU ranks need a real collectives transport
            # (the virtual test rig; TPU uses ICI/DCN natively)
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception as e:
                logger.warning(f"no gloo CPU collectives in this jax build "
                               f"({e}); multi-process CPU collectives may "
                               f"hang")
        if verbose:
            logger.info(f"Initializing JAX distributed: coordinator={coordinator} {kwargs}")
        jax.distributed.initialize(coordinator_address=coordinator, **kwargs)
    elif verbose:
        logger.info("Single-process JAX runtime; skipping multi-host rendezvous")
    _INITIALIZED = True


def get_world_size(group: Optional[AxisName] = None) -> int:
    """Devices in the group; with no group, all devices (chips = 'ranks')."""
    if group is None:
        return jax.device_count()
    try:
        return axis_size(group)  # inside shard_map/pmap trace
    except Exception:   # dstlint: disable=no-silent-except (probe: outside a trace axis_size raises; the mesh fallback below IS the outcome)
        mesh = _current_mesh()
        if mesh is not None:
            axes = (group,) if isinstance(group, str) else tuple(group)
            size = 1
            for a in axes:
                size *= mesh.shape.get(a, 1)
            return size
        return jax.device_count()


def get_rank(group: Optional[AxisName] = None):
    """Inside shard_map: traced index along the axis. Outside: process index."""
    if group is not None:
        return lax.axis_index(group)
    return jax.process_index()


def get_local_rank() -> int:
    return 0  # one process drives all local chips on TPU


def get_process_count() -> int:
    return jax.process_count()


def get_backend_name() -> str:
    return _COMM_BACKEND_NAME


def _current_mesh():
    try:
        from deepspeed_tpu.utils.jax_compat import get_abstract_mesh

        m = get_abstract_mesh()
        if m is not None and m.axis_names:
            return m
    except Exception:   # dstlint: disable=no-silent-except (probe: "no ambient mesh" is a normal state; None IS the outcome)
        pass
    return None


def _profile(op_name: str, tensor, kind: Optional[str] = None,
             group: Optional[AxisName] = None) -> None:
    if comms_logger.should_profile(op_name):
        try:
            size = get_msg_size_from_shape(tensor.shape, tensor.dtype)
        except Exception:   # dstlint: disable=no-silent-except (profiling must never break the collective; 0 is the explicit unknown-size record)
            size = 0
        group_size = None
        if kind is not None and group is not None:
            try:
                group_size = get_world_size(group)
            except Exception:   # dstlint: disable=no-silent-except (probe: no ambient mesh/axis; payload-only record IS the outcome)
                group_size = None
        # trace-time record: inside jit a collective has no host wall
        # time — mark the sample UNTIMED (None) instead of appending a
        # fabricated 0.0 that log_summary would average into latency
        comms_logger.append(op_name, None, size, kind=kind,
                            group_size=group_size)


# --------------------------------------------------------------------------
# Axis verbs — call inside shard_map with mesh axis names as `group`.
# --------------------------------------------------------------------------

def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisName = "data"):
    """reference comm.py:430 all_reduce → lax.psum/pmax/pmin family."""
    _profile("all_reduce", tensor, "psum", group)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = lax.psum(tensor, group)
        if op == ReduceOp.AVG:
            out = out / lax.psum(jnp.ones((), dtype=tensor.dtype), group)
        return out
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, group)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, group)
    if op == ReduceOp.PRODUCT:
        # sign-safe product: magnitude via log-sum, sign via negative-count
        # parity, zeros force a zero result
        abs_safe = jnp.where(tensor == 0, 1.0, jnp.abs(tensor))
        magnitude = jnp.exp(lax.psum(jnp.log(abs_safe), group))
        neg_parity = lax.psum((tensor < 0).astype(tensor.dtype), group) % 2
        sign = 1.0 - 2.0 * neg_parity
        any_zero = lax.pmax((tensor == 0).astype(tensor.dtype), group)
        return magnitude * sign * (1.0 - any_zero)
    raise NotImplementedError(f"ReduceOp {op} not supported on TPU backend")


def inference_all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisName = "tensor"):
    return all_reduce(tensor, op, group)


def all_gather(tensor, group: AxisName = "data", axis: int = 0, tiled: bool = True):
    """reference all_gather_into_tensor (comm/torch.py:78): concatenated
    gather along ``axis`` when tiled, stacked new leading dim otherwise."""
    _profile("all_gather", tensor, "all_gather", group)
    return lax.all_gather(tensor, group, axis=axis, tiled=tiled)


def all_gather_into_tensor(output_unused, tensor, group: AxisName = "data"):
    return all_gather(tensor, group, axis=0, tiled=True)


def reduce_scatter(tensor, group: AxisName = "data", axis: int = 0):
    """reference reduce_scatter_tensor → lax.psum_scatter (tiled)."""
    _profile("reduce_scatter", tensor, "reduce_scatter", group)
    return lax.psum_scatter(tensor, group, scatter_dimension=axis, tiled=True)


def all_to_all_single(tensor, group: AxisName = "data", split_axis: int = 0,
                      concat_axis: int = 0):
    """reference all_to_all_single (MoE dispatch). ``tensor`` must have its
    ``split_axis`` divisible by the group size."""
    _profile("all_to_all", tensor, "all_to_all", group)
    group_size = axis_size(group)
    return lax.all_to_all(tensor, group, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def broadcast(tensor, src: int = 0, group: AxisName = "data"):
    """reference comm.py:215 broadcast: every member gets src's value.

    Lowered as a masked psum, so that is what the wire accounting
    prices (2p(n-1)/n, matching the static SPMD inventory and the
    traffic XLA actually generates) — not an idealized p-byte tree."""
    _profile("broadcast", tensor, "psum", group)
    idx = lax.axis_index(group)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return lax.psum(masked, group)


def reduce(tensor, dst: int = 0, op: ReduceOp = ReduceOp.SUM,
           group: AxisName = "data"):
    """reference comm.py reduce: result valid on every member (SPMD has no
    cheaper single-destination form; dst kept for signature parity)."""
    return all_reduce(tensor, op, group)


def reduce_scatter_tensor(output_unused, tensor, op: ReduceOp = ReduceOp.SUM,
                          group: AxisName = "data"):
    """reference comm.py reduce_scatter_tensor (torch.py:118)."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise NotImplementedError("reduce_scatter supports SUM/AVG")
    out = reduce_scatter(tensor, group, axis=0)
    if op == ReduceOp.AVG:
        out = out / axis_size(group)
    return out


def all_gather_coalesced(tensor_list, group: AxisName = "data"):
    """reference all_gather_coalesced (comm/torch.py:135): one launch for
    many tensors. Under XLA the per-tensor gathers fuse into batched
    collectives, so this is the list-map — kept for API parity."""
    return [all_gather(t, group, axis=0, tiled=True) for t in tensor_list]


def reduce_scatter_coalesced(tensor_list, group: AxisName = "data"):
    """reference runtime/comm/coalesced_collectives.py:29: reduce-scatter a
    batch of tensors in one launch. Each flat tensor is padded to the group
    size and scattered; XLA coalesces the launches."""
    size = axis_size(group)
    outs = []
    for t in tensor_list:
        flat = t.reshape(-1)
        pad = (-flat.size) % size
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        outs.append(reduce_scatter(flat, group, axis=0))
    return outs


def ppermute(tensor, perm, group: AxisName = "pipe"):
    """Ring/point-to-point transfer — the pipeline p2p primitive
    (reference runtime/pipe/p2p.py send/recv become a single collective
    permute over the pipe axis)."""
    _profile("ppermute", tensor, "ppermute", group)
    return lax.ppermute(tensor, group, perm)


def _quant_chunks(x, chunk: int):
    """Per-chunk symmetric int8 quantization of ``x`` (last axis =
    ``chunk`` elements): scale = absmax/127 floored at 1e-10 (the same
    math as the KV-cache quantizer, models/llama.py quantize_kv_heads),
    payload = round-to-nearest-even clipped to [-127, 127]."""
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-10).astype(jnp.float32)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _dequant_chunks(q, scale):
    return q.astype(jnp.float32) * scale


def quantize_dequant_int8(x, chunk: int = None):
    """The int8 wire round-trip as a local transform: quantize ``x``
    per-chunk and dequantize it back (fp32). This is the precision loss
    one quantized hop applies to a value — the ZeRO
    ``communication_data_type: int8`` boundary uses it so the gradient
    numerics match what the quantized collective would deliver, while
    XLA still synthesizes the reduction from the sharding constraint."""
    from deepspeed_tpu.comm.collective_cost import QUANT_CHUNK

    chunk = chunk or QUANT_CHUNK
    orig_shape = x.shape
    v = x.astype(jnp.float32).reshape(-1)
    size = v.size
    padded = -(-max(size, 1) // chunk) * chunk
    if padded > size:
        v = jnp.concatenate([v, jnp.zeros((padded - size,), jnp.float32)])
    q, scale = _quant_chunks(v.reshape(-1, chunk), chunk)
    return _dequant_chunks(q, scale).reshape(-1)[:size].reshape(orig_shape)


def quantized_all_reduce(tensor, group: AxisName = "tensor",
                         chunk: int = None):
    """EQuARX-style int8 quantized ring all-reduce (SUM only).

    The fp32 value is padded to ``n`` equal shards (each a multiple of
    ``chunk`` elements) and reduced over a bidirectionless ring in two
    phases, every hop carrying an int8 payload + one fp32 scale per
    chunk (``collective_cost.quantized_ring_wire_bytes`` is the closed
    form; ~0.25x the fp32 ring's wire at chunk=256):

    1. **reduce-scatter** (n-1 hops): each device forwards its running
       partial quantized, dequant-accumulates the neighbour's; after
       n-1 hops device ``d`` owns the fully reduced shard ``(d+1)%n``.
    2. **all-gather** (n-1 hops): the owned shard is quantized ONCE and
       the same (q, scale) payload is forwarded around the ring; every
       device — including the owner — materializes the shard as
       ``dequant(q, scale)``, so all copies are bitwise identical (the
       replication invariant TP greedy decoding relies on).

    ``n`` folds to a static int at trace time, so the hop loop unrolls
    into plain ``ppermute`` equations the SPMD pass prices per-hop."""
    from deepspeed_tpu.comm.collective_cost import QUANT_CHUNK

    chunk = chunk or QUANT_CHUNK
    n = axis_size(group)
    if n <= 1:
        return tensor
    orig_dtype = tensor.dtype
    orig_shape = tensor.shape
    v = tensor.astype(jnp.float32).reshape(-1)
    size = v.size
    per = -(-max(size, 1) // n)          # ceil: elements per shard
    per = -(-per // chunk) * chunk       # rounded up to a chunk multiple
    total = per * n
    if total > size:
        v = jnp.concatenate([v, jnp.zeros((total - size,), jnp.float32)])
    data = v.reshape(n, per // chunk, chunk)

    me = lax.axis_index(group)
    fwd = [(i, (i + 1) % n) for i in range(n)]

    # phase 1: ring reduce-scatter — after hop s each device holds the
    # partial sum of s+2 contributions for shard (me - s - 1) % n
    acc = data[me]
    for s in range(n - 1):
        q, scale = _quant_chunks(acc, chunk)
        q = ppermute(q, fwd, group)
        scale = ppermute(scale, fwd, group)
        acc = data[(me - s - 1) % n] + _dequant_chunks(q, scale)

    # phase 2: ring all-gather of the reduced shards; quantize once and
    # forward the identical payload so every device reconstructs every
    # shard from the same (q, scale) bits
    q, scale = _quant_chunks(acc, chunk)
    out = jnp.zeros((n, per // chunk, chunk), jnp.float32)
    out = out.at[(me + 1) % n].set(_dequant_chunks(q, scale))
    for t in range(1, n):
        q = ppermute(q, fwd, group)
        scale = ppermute(scale, fwd, group)
        out = out.at[(me - t + 1) % n].set(_dequant_chunks(q, scale))

    return out.reshape(-1)[:size].reshape(orig_shape).astype(orig_dtype)


def send_forward(tensor, group: AxisName = "pipe"):
    """Shift +1 along the pipe ring (stage i → stage i+1)."""
    n = axis_size(group)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return ppermute(tensor, perm, group)


def send_backward(tensor, group: AxisName = "pipe"):
    """Shift -1 along the pipe ring (stage i → stage i-1)."""
    n = axis_size(group)
    perm = [(i, (i - 1) % n) for i in range(n)]
    return ppermute(tensor, perm, group)


def barrier(group: Optional[AxisName] = None):
    """Eager synchronization: drain outstanding device work."""
    t0 = time.perf_counter()
    for d in jax.devices():
        try:
            jax.device_put(0, d).block_until_ready()
        except Exception as e:
            # a device that cannot sync means the barrier did NOT cover
            # it — say so instead of silently weakening the guarantee
            logger.warning(f"barrier: device {d} failed to sync: {e}")
    # no payload/kind: a barrier moves no data, only waits — the latency
    # histogram is the signal (fleet collective-wait skew reads it)
    _record_measured("barrier", time.perf_counter() - t0, 0, None, None)


def monitored_barrier(group: Optional[AxisName] = None, timeout=None):
    barrier(group)


# --------------------------------------------------------------------------
# Eager helpers — host-side, for tests/utilities operating on global arrays.
# --------------------------------------------------------------------------

def eager_all_reduce_over_mesh(x, mesh, axis: str = "data", op: ReduceOp = ReduceOp.SUM):
    """Run an all_reduce across a mesh axis on a sharded global array."""
    from jax.sharding import NamedSharding, PartitionSpec

    t0 = time.perf_counter()
    fn = jax.jit(
        shard_map(
            lambda t: all_reduce(t, op, axis),
            mesh=mesh,
            in_specs=PartitionSpec(axis),
            out_specs=PartitionSpec(axis),
        )
    )
    out = fn(x)
    out.block_until_ready()
    # a REAL measured latency (host-boundary, post-block_until_ready):
    # timed comms-logger sample + registry histogram/byte counters
    _record_measured("all_reduce", time.perf_counter() - t0,
                     get_msg_size_from_shape(x.shape, x.dtype),
                     "psum", int(mesh.shape.get(axis, 1)),
                     op_label="all_reduce(eager)")
    return out


def eager_quantized_all_reduce_over_mesh(x, mesh, axis: str = "tensor",
                                         chunk: int = None):
    """Quantized-ring analogue of :func:`eager_all_reduce_over_mesh`:
    all-reduce a sharded global array over ``axis`` via
    :func:`quantized_all_reduce`, recording measured wire bytes priced
    by the SAME ``quantized_psum`` table entry the static budgets use."""
    from jax.sharding import PartitionSpec

    t0 = time.perf_counter()
    fn = jax.jit(
        shard_map(
            lambda t: quantized_all_reduce(t, axis, chunk),
            mesh=mesh,
            in_specs=PartitionSpec(axis),
            out_specs=PartitionSpec(axis),
        )
    )
    out = fn(x)
    out.block_until_ready()
    _record_measured("quantized_all_reduce", time.perf_counter() - t0,
                     get_msg_size_from_shape(x.shape, jnp.float32),
                     "quantized_psum", int(mesh.shape.get(axis, 1)),
                     op_label="quantized_all_reduce(eager)")
    return out


def log_summary():
    return comms_logger.log_summary()


def configure(deepspeed_config=None) -> None:
    if deepspeed_config is not None:
        comms_logger.configure(deepspeed_config.comms_logger)
