"""Per-device wire-byte arithmetic for collectives — ONE shared table.

Both byte accountings in the tree route through here so they cannot
drift apart:

- the RUNTIME side: ``comm/comms_logging.py`` records each verb's wire
  bytes when a collective is profiled (eager or trace-time), and
- the STATIC side: the dstlint SPMD pass
  (``tools/dstlint/spmdpass.py``) prices every collective equation it
  finds in an abstract trace when building ``comms_budgets.json``.

The model is the standard ring-algorithm cost on a ``n``-member group
(TPU ICI is a torus; XLA's collectives are ring/tree hybrids, but the
ring formula is the canonical per-device lower bound and is what every
roofline in PAPERS.md uses):

==============  =============================  =========================
kind            payload_bytes meaning          per-device wire bytes
==============  =============================  =========================
psum            the reduced value (per device)  2 * p * (n-1) / n
pmax / pmin     same as psum                    2 * p * (n-1) / n
reduce_scatter  the full pre-scatter value      p * (n-1) / n
all_gather      this device's input shard       p * (n-1)
all_to_all      this device's full input        p * (n-1) / n
ppermute        the permuted value              p
broadcast       the value                       p
quantized_psum  the fp32 reduced value          see below
shard/reshard   constraint boundary (no wire)   0
==============  =============================  =========================

``psum`` counts the reduce-scatter + all-gather phases of a ring
all-reduce; ``all_gather`` is priced from the INPUT shard (each device
receives n-1 foreign shards of that size); ``ppermute`` sends the whole
value exactly once regardless of group size.

``quantized_psum`` is the EQuARX-style int8 quantized ring all-reduce
(``comm.quantized_all_reduce``): 2(n-1) point-to-point hops per device,
each carrying the per-shard int8 payload plus one fp32 scale per
``QUANT_CHUNK``-element chunk. Its jaxpr decomposes into plain
``ppermute`` equations, so the SPMD pass prices the hops individually;
:func:`quantized_ring_wire_bytes` is the closed form the two accountings
share (the sum of those hop prices), exposed through ``wire_bytes`` for
the measured side.
"""

from typing import Optional

#: elements per quantization chunk (one fp32 scale per chunk) — shared
#: by the runtime collective and the static pricing so the overhead
#: term (4/chunk per element) cannot drift between the two accountings
QUANT_CHUNK = 256

#: collective kinds the table prices; anything else costs 0 wire bytes
REDUCTION_KINDS = ("psum", "pmax", "pmin", "reduce_scatter")
WIRE_KINDS = REDUCTION_KINDS + ("all_gather", "all_to_all", "ppermute",
                                "broadcast")

#: jaxpr primitive name → canonical collective kind
PRIMITIVE_KINDS = {
    "psum": "psum",
    "psum2": "psum",            # shard_map spelling on jax 0.4.x
    "pmax": "pmax",
    "pmin": "pmin",
    "psum_scatter": "reduce_scatter",
    "reduce_scatter": "reduce_scatter",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
    "pbroadcast": "broadcast",
}


def wire_bytes(kind: str, payload_bytes: int, group_size: int) -> int:
    """Per-device bytes a ``kind`` collective moves over the interconnect
    for a ``payload_bytes`` payload on a ``group_size``-member group.
    See the module table for what ``payload_bytes`` means per kind."""
    n = int(group_size)
    p = int(payload_bytes)
    if n <= 1 or p <= 0:
        return 0
    if kind in ("psum", "pmax", "pmin"):
        return 2 * p * (n - 1) // n
    if kind == "reduce_scatter":
        return p * (n - 1) // n
    if kind == "all_gather":
        return p * (n - 1)
    if kind == "all_to_all":
        return p * (n - 1) // n
    if kind == "ppermute":
        return p
    if kind == "broadcast":
        return p
    if kind == "quantized_psum":
        return quantized_ring_wire_bytes(p, n)
    return 0


def quantized_ring_wire_bytes(payload_bytes: int, group_size: int,
                              chunk: int = QUANT_CHUNK,
                              elem_bytes: int = 4,
                              scale_bytes: int = 4) -> int:
    """Per-device wire bytes of the int8 quantized ring all-reduce for a
    ``payload_bytes`` fp32 value on a ``group_size``-member group.

    The ring pads the flat value to ``n`` equal shards of a ``chunk``
    multiple, then runs n-1 reduce-scatter hops + n-1 all-gather hops;
    every hop moves the int8 shard (1 byte/element) plus one fp32 scale
    per chunk: ``2(n-1) * per * (1 + scale_bytes/chunk)`` vs the fp32
    ring's ``2 * p * (n-1)/n`` — a ~(1+4/chunk)/elem_bytes ≈ 0.25x
    payload ratio at chunk=256."""
    n = int(group_size)
    p = int(payload_bytes)
    if n <= 1 or p <= 0:
        return 0
    elems = max(-(-p // elem_bytes), 1)
    per = -(-elems // n)                 # ceil: elements per shard
    per = -(-per // chunk) * chunk       # rounded up to a chunk multiple
    hop = per + (per // chunk) * scale_bytes
    return 2 * (n - 1) * hop


def payload_bytes_from_shape(shape, dtype) -> int:
    """bytes of one array — the shared shape×itemsize arithmetic."""
    import numpy as np

    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def collective_kind(primitive_name: str) -> Optional[str]:
    """Canonical kind for a jaxpr primitive name, or None when the
    primitive is not a collective."""
    return PRIMITIVE_KINDS.get(primitive_name)
