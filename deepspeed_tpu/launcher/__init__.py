from deepspeed_tpu.launcher.runner import (
    fetch_hostfile,
    main,
    parse_inclusion_exclusion,
)
