"""`dst` launcher CLI.

TPU-native analogue of reference ``deepspeed/launcher/runner.py:377``: parses
a hostfile (``host slots=N``), applies ``--include/--exclude`` filters, and
launches the training script. Differences driven by the platform:

- one process per HOST (JAX drives all local chips from one process), not
  one per chip — so "slots" counts chips for bookkeeping but process count
  equals host count;
- rendezvous env is the JAX coordinator (``DS_TPU_COORDINATOR`` +
  process_id/num_processes) instead of MASTER_ADDR/RANK per GPU;
- multi-node transport is plain ssh fan-out (pdsh-style) — TPU pods also
  commonly launch via GKE/gcloud, for which this module only needs to emit
  the env block (``--print_env``).
"""

import argparse
import os
import shlex
import signal
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "JAX_PLATFORMS",
               "XLA_FLAGS", "TPU_NAME"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="dst launcher — run a deepspeed_tpu training script on "
                    "one or many TPU hosts")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile: one 'hostname slots=N' per line")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="e.g. host1@host2:0,2 — hosts (and chips) to include")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="hosts/chips to exclude (mutually exclusive with -i)")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_chips", dest="num_gpus",
                        type=int, default=-1)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "local", "print", "pdsh", "slurm",
                                 "openmpi", "mpich"],
                        help="ssh fan-out, local single-host, print the "
                             "per-host commands without running, or a "
                             "scheduler backend (pdsh/slurm/openmpi/mpich — "
                             "reference multinode_runner.py)")
    parser.add_argument("--print_env", action="store_true",
                        help="print the env block each host receives")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--autotuning_results_dir", type=str,
                        default="autotuning_results",
                        help="where the Autotuner wrote its results "
                        "(AutotuningConfig.results_dir)")
    parser.add_argument("--autotuning", type=str, default="",
                        choices=["", "tune", "run"],
                        help="tune: user script should run the Autotuner "
                        "(exported as DS_TPU_AUTOTUNING); run: launch with "
                        "the tuned autotuning_results/ds_config_optimal.json "
                        "(exported as DS_TPU_CONFIG_OVERRIDE)")
    parser.add_argument("--save_pid", action="store_true")
    parser.add_argument("user_script", type=str, help="training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(hostfile_path: str) -> "OrderedDict[str, int]":
    """Parse 'hostname slots=N' lines (reference runner.py:189)."""
    resources: "OrderedDict[str, int]" = OrderedDict()
    if not os.path.isfile(hostfile_path):
        return resources
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                host, slots = line.split()
                key, count = slots.split("=")
                if key != "slots":
                    raise ValueError(key)
                resources[host] = int(count)
            except ValueError:
                raise ValueError(f"Hostfile syntax error: {line!r} "
                                 "(expected 'hostname slots=N')")
    return resources


def _parse_filter(spec: str) -> Dict[str, Optional[List[int]]]:
    """'h1@h2:0,2' -> {h1: None, h2: [0, 2]} (None = all slots)."""
    out: Dict[str, Optional[List[int]]] = {}
    for part in spec.split("@"):
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":")
            out[host] = sorted(int(s) for s in slots.split(","))
        else:
            out[part] = None
    return out


def parse_inclusion_exclusion(resources: Dict[str, int], include: str,
                              exclude: str) -> "OrderedDict[str, List[int]]":
    """Apply --include/--exclude (reference runner.py:244)."""
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")
    full = OrderedDict((h, list(range(n))) for h, n in resources.items())
    if include:
        spec = _parse_filter(include)
        out = OrderedDict()
        for host, slots in spec.items():
            if host not in full:
                raise ValueError(f"include host {host} not in hostfile")
            chosen = slots if slots is not None else full[host]
            bad = set(chosen) - set(full[host])
            if bad:
                raise ValueError(f"include slots {sorted(bad)} out of range for {host}")
            out[host] = chosen
        return out
    if exclude:
        spec = _parse_filter(exclude)
        out = OrderedDict()
        for host, slots in full.items():
            if host in spec:
                if spec[host] is None:
                    continue
                keep = [s for s in slots if s not in spec[host]]
                if keep:
                    out[host] = keep
            else:
                out[host] = slots
        return out
    return full


def build_host_env(host_index: int, num_hosts: int, coordinator: str,
                   extra_env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    env = {
        "DS_TPU_COORDINATOR": coordinator,
        "DS_TPU_NUM_PROCESSES": str(num_hosts),
        "DS_TPU_PROCESS_ID": str(host_index),
    }
    for name in EXPORT_ENVS:
        if name in os.environ:
            env[name] = os.environ[name]
    if os.path.isfile(DEEPSPEED_ENVIRONMENT_NAME):
        with open(DEEPSPEED_ENVIRONMENT_NAME) as f:
            for line in f:
                if "=" in line:
                    k, v = line.strip().split("=", 1)
                    env[k] = v
    if extra_env:
        env.update(extra_env)
    return env


def build_autotune_env(args) -> Dict[str, str]:
    """--autotuning exports (shared by the ssh and scheduler launch paths)."""
    autotune_env: Dict[str, str] = {}
    if getattr(args, "autotuning", ""):
        autotune_env["DS_TPU_AUTOTUNING"] = args.autotuning
        if args.autotuning == "run":
            optimal = os.path.join(
                getattr(args, "autotuning_results_dir", "autotuning_results"),
                "ds_config_optimal.json")
            if not os.path.isfile(optimal):
                raise FileNotFoundError(
                    f"--autotuning run: {optimal} not found; run "
                    "--autotuning tune first")
            autotune_env["DS_TPU_CONFIG_OVERRIDE"] = os.path.abspath(optimal)
    return autotune_env


def resolve_coordinator(args, hosts: List[str]) -> str:
    return f"{args.master_addr or hosts[0]}:{args.master_port}"


def build_commands(args, active: "OrderedDict[str, List[int]]"
                   ) -> List[Tuple[str, List[str], Dict[str, str]]]:
    hosts = list(active.keys())
    coordinator = resolve_coordinator(args, hosts)
    cmds = []
    autotune_env = build_autotune_env(args)
    for idx, host in enumerate(hosts):
        env = build_host_env(idx, len(hosts), coordinator,
                             extra_env=autotune_env)
        payload = [sys.executable, args.user_script] + list(args.user_args)
        if args.launcher == "ssh" and (len(hosts) > 1 or args.force_multi):
            env_str = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
            remote = f"cd {shlex.quote(os.getcwd())} && {env_str} " + \
                " ".join(shlex.quote(p) for p in payload)
            cmd = ["ssh", "-o", "StrictHostKeyChecking=no", host, remote]
        else:
            cmd = payload
        cmds.append((host, cmd, env))
    return cmds


def main(args=None) -> int:
    args = parse_args(args)
    resources = fetch_hostfile(args.hostfile)
    if not resources:
        # single-node fallback (reference: localhost with all visible chips)
        n = args.num_gpus if args.num_gpus > 0 else 0
        resources = OrderedDict([("localhost", n or 8)])
    active = parse_inclusion_exclusion(resources, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[: args.num_nodes])
    if not active:
        raise ValueError("no hosts remain after include/exclude filtering")

    # scheduler-delegated fan-out (reference multinode_runner.py backends):
    # one local command whose backend starts every host's worker; node ranks
    # come from the scheduler (SLURM_NODEID / OMPI_COMM_WORLD_RANK) or the
    # hostfile order for pdsh
    from deepspeed_tpu.launcher.multinode_runner import RUNNERS, get_runner

    if args.launcher in RUNNERS:
        world_info = OrderedDict((h, len(s)) for h, s in active.items())
        runner = get_runner(args.launcher, args, world_info)
        hosts = list(active.keys())
        coordinator = resolve_coordinator(args, hosts)
        env = build_host_env(0, len(hosts), coordinator,
                             extra_env=build_autotune_env(args))
        env.pop("DS_TPU_PROCESS_ID", None)   # per-host rank set by backend
        cmd = runner.get_cmd(env, active)
        if args.print_env:
            print(" ".join(shlex.quote(c) for c in cmd))
            return 0
        if not runner.backend_exists():
            logger.error(f"launcher backend {args.launcher!r} not found on "
                         f"PATH; command would be: "
                         f"{' '.join(shlex.quote(c) for c in cmd)}")
            return 1
        logger.info(f"{args.launcher} launch: {' '.join(cmd[:6])}...")
        return subprocess.call(cmd)

    cmds = build_commands(args, active)
    if args.print_env or args.launcher == "print":
        for host, cmd, env in cmds:
            print(f"# {host}")
            for k, v in env.items():
                print(f"export {k}={v}")
            print(" ".join(shlex.quote(c) for c in cmd))
        return 0

    procs = []
    for host, cmd, env in cmds:
        full_env = dict(os.environ)
        full_env.update(env)
        logger.info(f"launching on {host}: {' '.join(cmd[:4])}...")
        procs.append(subprocess.Popen(cmd, env=full_env))

    def _terminate(signum, frame):
        for p in procs:
            if p.poll() is None:
                p.terminate()
        sys.exit(1)

    signal.signal(signal.SIGINT, _terminate)
    signal.signal(signal.SIGTERM, _terminate)

    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
