"""Multi-node runners (reference ``deepspeed/launcher/multinode_runner.py``:
PDSH/OpenMPI/MPICH/SLURM/MVAPICH classes with ``backend_exists`` +
``get_cmd``).

TPU pods are driven the same way the reference drives GPU clusters — one
agent process per host — so the runner contract ports directly: each runner
renders the command that starts every host's worker with the JAX
coordinator env (``build_host_env``). PDSH fans out over the hostfile,
SLURM delegates fan-out to ``srun`` (GKE/XPK-style allocations), MPI
runners use ``mpirun`` rank placement with env forwarded per rank.
"""

import os
import shlex
import shutil
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


class MultiNodeRunner:
    name = "base"

    def __init__(self, args, world_info: "OrderedDict[str, int]"):
        self.args = args
        self.world_info = world_info          # host -> slots
        self.user_script = args.user_script
        self.user_args = list(args.user_args)
        self.exports: Dict[str, str] = {}

    def backend_exists(self) -> bool:
        raise NotImplementedError

    def get_cmd(self, environment: Dict[str, str],
                active_resources: "OrderedDict[str, List[int]]") -> List[str]:
        raise NotImplementedError

    def add_export(self, key: str, val: str) -> None:
        self.exports[key.strip()] = val.strip()

    @property
    def num_nodes(self) -> int:
        return len(self.world_info)

    def _payload(self) -> List[str]:
        return [sys.executable, self.user_script] + self.user_args


class PDSHRunner(MultiNodeRunner):
    """reference multinode_runner.py:51 — pdsh fan-out over the hostfile."""

    name = "pdsh"

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources) -> List[str]:
        environment = dict(environment)
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active_resources.keys())
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in {**environment, **self.exports}.items())
        remote = (f"{exports}cd {shlex.quote(os.getcwd())}; "
                  + " ".join(shlex.quote(p) for p in self._payload()))
        return ["pdsh", "-S", "-f", "1024", "-w", hosts, remote]


class SlurmRunner(MultiNodeRunner):
    """reference multinode_runner.py:231 — srun-delegated placement."""

    name = "slurm"

    def backend_exists(self) -> bool:
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources) -> List[str]:
        # host filtering already happened upstream (parse_inclusion_exclusion)
        # — place srun exactly on the surviving hosts via --nodelist
        cmd = ["srun", "-n", str(self.num_nodes), "--ntasks-per-node", "1",
               "--nodelist", ",".join(active_resources.keys())]
        exports = ["--export=ALL"
                   + "".join(f",{k}={v}"
                             for k, v in {**environment,
                                          **self.exports}.items())]
        return cmd + exports + self._payload()


class OpenMPIRunner(MultiNodeRunner):
    """reference multinode_runner.py:107 — mpirun with per-rank env."""

    name = "openmpi"

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources) -> List[str]:
        hosts = ",".join(f"{h}:1" for h in active_resources)
        cmd = ["mpirun", "-n", str(self.num_nodes), "--host", hosts,
               "--allow-run-as-root"]
        for k, v in {**environment, **self.exports}.items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + self._payload()


class MPICHRunner(OpenMPIRunner):
    """reference multinode_runner.py:160 — mpiexec variant."""

    name = "mpich"

    def backend_exists(self) -> bool:
        return shutil.which("mpiexec") is not None

    def get_cmd(self, environment, active_resources) -> List[str]:
        hosts = ",".join(active_resources)
        cmd = ["mpiexec", "-n", str(self.num_nodes), "-hosts", hosts]
        for k, v in {**environment, **self.exports}.items():
            cmd += ["-genv", k, v]
        return cmd + self._payload()


RUNNERS = {r.name: r for r in
           (PDSHRunner, SlurmRunner, OpenMPIRunner, MPICHRunner)}


def get_runner(name: str, args, world_info) -> Optional[MultiNodeRunner]:
    cls = RUNNERS.get(name)
    if cls is None:
        return None
    runner = cls(args, world_info)
    if not runner.backend_exists():
        logger.warning(f"launcher backend {name!r} not found on PATH")
    return runner
