"""deepspeed_tpu — a TPU-native large-model training & inference framework.

Brand-new JAX/XLA/Pallas implementation of the capability surface of
DeepSpeed v0.9.3 (reference layout documented in SURVEY.md): ZeRO-style
sharded training, tensor/pipeline/expert/sequence parallelism over a device
mesh, an inference engine with TP sharding and KV caching, checkpointing,
profiling, and the auxiliary subsystems — all designed for XLA's compilation
model rather than translated from CUDA.

Public entry points mirror the reference (``deepspeed/__init__.py:58,260``):

    engine = deepspeed_tpu.initialize(model=..., config={...},
                                      sample_batch=...)
    loss = engine.train_batch(batch)

    infer = deepspeed_tpu.init_inference(model=..., config={...})
"""

import os

from deepspeed_tpu import comm  # noqa: F401
from deepspeed_tpu.runtime import zero  # noqa: F401  (deepspeed.zero parity)
from deepspeed_tpu.runtime.config import DeepSpeedConfig  # noqa: F401
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import logger  # noqa: F401

__version__ = "0.1.0"
__git_branch__ = "main"


def initialize(model=None,
               config=None,
               loss_fn=None,
               params=None,
               mesh=None,
               sharding_rules=None,
               lr_scheduler=None,
               sample_batch=None,
               args=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               dist_init_required=None,
               config_params=None,
               model_config=None,
               lora_adapters=None,
               num_micro=None):
    """Create a training engine (reference ``deepspeed.initialize``).

    Returns the engine. (The reference returns a 4-tuple
    ``(engine, optimizer, dataloader, scheduler)``; on TPU the optimizer and
    scheduler live inside the jitted step, so the engine is the single
    handle. Use ``initialize_legacy`` for tuple-unpacking parity.)
    """
    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config"):
        config = args.deepspeed_config
    # `dst --autotuning run` exports the tuned config (launcher/runner.py)
    override = os.environ.get("DS_TPU_CONFIG_OVERRIDE")
    if override and not isinstance(config, DeepSpeedConfig):
        import json as _json

        def _deep_merge(base, over):
            out = dict(base)
            for k, v in over.items():
                if isinstance(v, dict) and isinstance(out.get(k), dict):
                    out[k] = _deep_merge(out[k], v)
                else:
                    out[k] = v
            return out

        if isinstance(config, str):          # config given as a file path
            with open(config) as f:
                config = _json.load(f)
        with open(override) as f:
            tuned = _json.load(f)
        config = _deep_merge(config or {}, tuned)

    # engine dispatch (reference deepspeed/__init__.py:150-190): hybrid
    # engine when hybrid_engine.enabled, else the core engine (the pipeline
    # engine is the core engine — PP is a mesh axis, not a subclass)
    resolved = config if isinstance(config, DeepSpeedConfig) \
        else DeepSpeedConfig(config or {},
                             world_size=mesh.size if mesh is not None else None)
    common = dict(model=model, config=resolved, loss_fn=loss_fn, params=params,
                  mesh=mesh, sharding_rules=sharding_rules,
                  lr_scheduler=lr_scheduler, sample_batch=sample_batch)
    if resolved.hybrid_engine.enabled:
        from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

        engine = DeepSpeedHybridEngine(model_config=model_config,
                                       lora_adapters=lora_adapters, **common)
    elif resolved.mesh.pipe > 1 and loss_fn is None:
        # pipe axis requested → pipeline engine (analogue of the reference's
        # PipelineModule dispatch, deepspeed/__init__.py:150-190)
        from deepspeed_tpu.parallel.mesh import make_mesh as _mk
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

        if common["mesh"] is None:
            common["mesh"] = _mk(resolved.mesh)
        common.pop("loss_fn")
        engine = PipelineEngine(model_config=model_config,
                                num_micro=num_micro, **common)
    else:
        engine = DeepSpeedEngine(**common)
    if training_data is not None:
        # reference deepspeed_io wiring (engine.py:1571): attach a loader
        # sized to the global batch; train_batch() with no argument
        # consumes it
        engine.deepspeed_io(training_data)
    return engine


def initialize_legacy(*posargs, **kwargs):
    """4-tuple form for reference API parity."""
    engine = initialize(*posargs, **kwargs)
    return (engine, engine.optimizer, engine.training_dataloader,
            engine.client_lr_scheduler)


def init_inference(model=None, config=None, **kwargs):
    """Create an inference engine (reference ``deepspeed.init_inference``)."""
    from deepspeed_tpu.inference.engine import InferenceEngine

    return InferenceEngine(model=model, config=config, **kwargs)


def init_distributed(dist_backend="xla-ici", **kwargs):
    comm.init_distributed(dist_backend=dist_backend, **kwargs)
