"""Metric event sinks (reference ``deepspeed/monitor/monitor.py:29``).

``MonitorMaster`` fans out (name, value, step) events to TensorBoard, WandB,
and CSV sinks, each config-gated. Event names keep the reference's contract
(``Train/Samples/train_loss`` etc., SURVEY §8.6) so dashboards port
unchanged. Only the JAX process 0 writes (reference checks rank 0).
"""

import os
from typing import List, Tuple

import jax

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, config):
        self.enabled = config.enabled

    def write_events(self, event_list: List[Event]) -> None:
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if not self.enabled or jax.process_index() != 0:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter

            log_dir = os.path.join(config.output_path or "./runs", config.job_name)
            self.summary_writer = SummaryWriter(log_dir=log_dir)
        except Exception as e:  # tensorboard optional
            logger.warning(f"TensorBoard unavailable ({e}); disabling tb monitor")

    def write_events(self, event_list: List[Event]) -> None:
        if self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, float(value), int(step))
        self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self._wandb = None
        if not self.enabled or jax.process_index() != 0:
            return
        try:
            import wandb

            wandb.init(project=config.project, group=config.group, entity=config.team)
            self._wandb = wandb
        except Exception as e:
            logger.warning(f"wandb unavailable ({e}); disabling wandb monitor")

    def write_events(self, event_list: List[Event]) -> None:
        if self._wandb is None:
            return
        for name, value, step in event_list:
            self._wandb.log({name: float(value)}, step=int(step))


class csvMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.filehandles = {}
        self.output_path = None
        if not self.enabled or jax.process_index() != 0:
            return
        self.output_path = os.path.join(config.output_path or "./csv_logs",
                                        config.job_name)
        os.makedirs(self.output_path, exist_ok=True)

    def write_events(self, event_list: List[Event]) -> None:
        if self.output_path is None:
            return
        for name, value, step in event_list:
            fname = name.replace("/", "_") + ".csv"
            path = os.path.join(self.output_path, fname)
            if name not in self.filehandles:
                self.filehandles[name] = open(path, "a")
            self.filehandles[name].write(f"{int(step)},{float(value)}\n")
            self.filehandles[name].flush()


class MonitorMaster(Monitor):
    """Fan-out master (reference monitor/monitor.py:29)."""

    def __init__(self, ds_config):
        self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb_monitor = WandbMonitor(ds_config.wandb)
        self.csv_monitor = csvMonitor(ds_config.csv_monitor)
        self.enabled = (self.tb_monitor.enabled or self.wandb_monitor.enabled
                        or self.csv_monitor.enabled)

    def write_events(self, event_list: List[Event]) -> None:
        if jax.process_index() != 0:
            return
        for sink in (self.tb_monitor, self.wandb_monitor, self.csv_monitor):
            if sink.enabled:
                sink.write_events(event_list)
