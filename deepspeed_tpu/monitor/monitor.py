"""Metric event sinks (reference ``deepspeed/monitor/monitor.py:29``).

``MonitorMaster`` fans out (name, value, step) events to JSONL,
TensorBoard, WandB, and CSV sinks, each config-gated. Event names keep
the reference's contract (``Train/Samples/train_loss`` etc., SURVEY
§8.6) so dashboards port unchanged. Only the JAX process 0 writes
(reference checks rank 0).

The JSONL sink is the DEFAULT backend: dependency-free (stdlib json to
one append-only file), it activates automatically whenever monitoring
is enabled — before it, a torch-free install with ``tensorboard:
{enabled: true}`` silently lost every event. ``jsonl_monitor:
{enabled: false}`` opts out; ``{enabled: true}`` turns monitoring on by
itself.

dstrace integration (docs/OBSERVABILITY.md): :meth:`MonitorMaster.
write_registry` drains a ``MetricsRegistry`` snapshot into the same
event stream (counters/gauges verbatim, histograms as their summary
stats), so the training engine's registry — step timers, throughput,
ZeRO reduction bytes, comms wire totals — reaches every configured
dashboard without a second plumbing path.
"""

import json
import os
from typing import List, Tuple

import jax

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, config):
        self.enabled = config.enabled

    def write_events(self, event_list: List[Event]) -> None:
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if not self.enabled or jax.process_index() != 0:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter

            log_dir = os.path.join(config.output_path or "./runs", config.job_name)
            self.summary_writer = SummaryWriter(log_dir=log_dir)
        except Exception as e:  # tensorboard optional
            logger.warning(f"TensorBoard unavailable ({e}); disabling tb monitor")

    def write_events(self, event_list: List[Event]) -> None:
        if self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, float(value), int(step))
        self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self._wandb = None
        if not self.enabled or jax.process_index() != 0:
            return
        try:
            import wandb

            wandb.init(project=config.project, group=config.group, entity=config.team)
            self._wandb = wandb
        except Exception as e:
            logger.warning(f"wandb unavailable ({e}); disabling wandb monitor")

    def write_events(self, event_list: List[Event]) -> None:
        if self._wandb is None:
            return
        for name, value, step in event_list:
            self._wandb.log({name: float(value)}, step=int(step))


class csvMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.filehandles = {}
        self.output_path = None
        if not self.enabled or jax.process_index() != 0:
            return
        self.output_path = os.path.join(config.output_path or "./csv_logs",
                                        config.job_name)
        os.makedirs(self.output_path, exist_ok=True)

    def write_events(self, event_list: List[Event]) -> None:
        if self.output_path is None:
            return
        for name, value, step in event_list:
            fname = name.replace("/", "_") + ".csv"
            path = os.path.join(self.output_path, fname)
            if name not in self.filehandles:
                self.filehandles[name] = open(path, "a")
            self.filehandles[name].write(f"{int(step)},{float(value)}\n")
            self.filehandles[name].flush()


class JSONLMonitor(Monitor):
    """Dependency-free default sink: one append-only ``events.jsonl``
    (``{"name", "value", "step"}`` per line) under
    ``output_path/job_name``. ``config.enabled`` is tri-state: None =
    AUTO (on whenever any monitoring is on — ``auto_enabled``), so a
    stack with no torch/tensorboard/wandb still lands its events on
    disk instead of silently dropping them."""

    def __init__(self, config, auto_enabled: bool = False):
        self.enabled = (auto_enabled if config.enabled is None
                        else bool(config.enabled))
        self._fh = None
        if not self.enabled or jax.process_index() != 0:
            return
        out_dir = os.path.join(config.output_path or "./jsonl_logs",
                               config.job_name)
        try:
            os.makedirs(out_dir, exist_ok=True)
            self.path = os.path.join(out_dir, "events.jsonl")
            self._fh = open(self.path, "a")
        except OSError as e:
            logger.warning(f"jsonl monitor unusable ({e}); disabling")
            self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if self._fh is None:
            return
        for name, value, step in event_list:
            self._fh.write(json.dumps({"name": name, "value": float(value),
                                       "step": int(step)}) + "\n")
        self._fh.flush()


class PrometheusFileMonitor(Monitor):
    """Prometheus TEXTFILE sink (dstprof, docs/OBSERVABILITY.md): each
    registry drain rewrites ``output_path/job_name/metrics.prom`` with
    the FULL exposition rendering of the engine's metrics registry —
    counters/gauges and real ``_bucket/_sum/_count`` histograms, not
    the flattened (name, value, step) events — for node-exporter's
    textfile collector to pick up. Atomic replace (write + rename): a
    collector must never read a half-written exposition. Plain events
    (``write_events``) are ignored; this sink only speaks registry."""

    def __init__(self, config):
        super().__init__(config)
        self.path = None
        if not self.enabled or jax.process_index() != 0:
            self.enabled = False
            return
        out_dir = os.path.join(config.output_path or "./prometheus",
                               config.job_name)
        try:
            os.makedirs(out_dir, exist_ok=True)
            self.path = os.path.join(out_dir, "metrics.prom")
        except OSError as e:
            logger.warning(f"prometheus monitor unusable ({e}); disabling")
            self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        pass                            # registry-only sink

    def write_registry_text(self, registry, step: int) -> None:
        if not self.enabled or self.path is None:
            return
        from deepspeed_tpu.observability import prometheus_text

        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(prometheus_text(registry))
        os.replace(tmp, self.path)


class MonitorMaster(Monitor):
    """Fan-out master (reference monitor/monitor.py:29)."""

    def __init__(self, ds_config):
        self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb_monitor = WandbMonitor(ds_config.wandb)
        self.csv_monitor = csvMonitor(ds_config.csv_monitor)
        self.prometheus_monitor = PrometheusFileMonitor(
            ds_config.prometheus_monitor)
        # the dependency-free default: auto-on when anything above asked
        # for monitoring (or when explicitly enabled by itself)
        any_other = (self.tb_monitor.enabled or self.wandb_monitor.enabled
                     or self.csv_monitor.enabled
                     or self.prometheus_monitor.enabled)
        self.jsonl_monitor = JSONLMonitor(ds_config.jsonl_monitor,
                                          auto_enabled=any_other)
        self.enabled = any_other or self.jsonl_monitor.enabled

    def write_events(self, event_list: List[Event]) -> None:
        if jax.process_index() != 0:
            return
        for sink in (self.tb_monitor, self.wandb_monitor,
                     self.csv_monitor, self.jsonl_monitor):
            if sink.enabled:
                sink.write_events(event_list)

    def write_registry(self, registry, step: int,
                       prefix: str = "Registry") -> None:
        """Drain a dstrace ``MetricsRegistry`` snapshot into the event
        stream: counters and gauges verbatim, histograms as their
        summary statistics (count/sum/mean/p50/p95/p99) — the path by
        which the training registry (timers, throughput, ZeRO reduction
        bytes, comms wire totals) reaches every configured sink."""
        snap = registry.snapshot()
        events: List[Event] = []
        for name, v in snap.get("counters", {}).items():
            events.append((f"{prefix}/{name}", v, step))
        for name, v in snap.get("gauges", {}).items():
            events.append((f"{prefix}/{name}", v, step))
        for name, stats in snap.get("histograms", {}).items():
            for stat, v in stats.items():
                events.append((f"{prefix}/{name}/{stat}", v, step))
        # collector sections (comms wire totals, prefix-cache stats)
        # sit at the snapshot's top level under their own names —
        # drain their numeric leaves too, or the comm bytes the
        # registry exists to absorb would never reach a dashboard
        core = {"counters", "gauges", "histograms"}
        for section, data in snap.items():
            if section in core or not isinstance(data, dict):
                continue
            for name, v in data.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    events.append((f"{prefix}/{section}.{name}", v, step))
        if events:
            self.write_events(events)
        # the prometheus sink renders the registry itself (exposition
        # histograms need raw buckets the event tuples cannot carry)
        self.prometheus_monitor.write_registry_text(registry, step)
