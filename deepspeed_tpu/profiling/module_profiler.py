"""Per-module FLOPs attribution from jaxpr traversal.

TPU-native analogue of the reference flops profiler's per-module tree
(``profiling/flops_profiler/profiler.py:23``): the reference hooks torch
functionals and attributes MACs to the ``nn.Module`` hierarchy; here every
jaxpr equation carries the flax scope path in ``source_info.name_stack``
(e.g. ``LlamaModel/blocks/block/attn/q_proj``), so one traversal of the
traced program yields the same per-module breakdown — *before* XLA fusion,
which is exactly the granularity the reference reports (its counts are
pre-kernel-fusion too).

Control flow: ``scan`` bodies multiply by trip count, ``cond`` takes the
widest branch, ``while`` counts one iteration (trip count is dynamic —
flagged in the report). The tree's node totals are sums of their children
plus own-scope flops by construction, so the root row IS the whole-program
total of this accounting.
"""

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _dot_flops(eqn) -> float:
    """2·batch·M·N·K from dot_general dimension numbers."""
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = float(np.prod([a.shape[i] for i in lb], dtype=np.float64)) \
        if lb else 1.0
    k = float(np.prod([a.shape[i] for i in lc], dtype=np.float64)) \
        if lc else 1.0
    m = float(np.prod([a.shape[i] for i in range(a.ndim)
                       if i not in lc and i not in lb], dtype=np.float64))
    n = float(np.prod([b.shape[i] for i in range(b.ndim)
                       if i not in rc and i not in rb], dtype=np.float64))
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    fg = int(eqn.params.get("feature_group_count", 1))
    # per output element: 2 · (kernel spatial · in_channels / groups)
    per_out = 2.0 * float(np.prod(rhs.shape[2:], dtype=np.float64)) \
        * rhs.shape[1] / max(fg, 1)
    return float(np.prod(out.shape, dtype=np.float64)) * per_out


_ELEMENTWISE = {
    "add", "sub", "mul", "div", "neg", "exp", "log", "tanh", "sin", "cos",
    "rsqrt", "sqrt", "pow", "integer_pow", "max", "min", "abs", "sign",
    "logistic", "erf", "floor", "ceil", "round", "rem", "square", "cbrt",
    "atan2", "expm1", "log1p", "clamp", "select_n", "nextafter",
}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
           "reduce_and", "reduce_or", "argmax", "argmin",
           "cumsum", "cumprod", "cummax", "cummin"}


def _prim_flops(eqn) -> float:
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _ELEMENTWISE:
        return float(np.prod(eqn.outvars[0].aval.shape, dtype=np.float64))
    if name in _REDUCE:
        return float(np.prod(eqn.invars[0].aval.shape, dtype=np.float64))
    return 0.0


def _inner_jaxprs(eqn) -> List[Tuple[Any, float, bool]]:
    """(closed_jaxpr, multiplier, is_estimate) nested inside ``eqn``."""
    name = eqn.primitive.name
    p = eqn.params
    if name == "scan":
        return [(p["jaxpr"], float(p.get("length", 1)), False)]
    if name == "while":
        # dynamic trip count: count ONE iteration, flagged upstream
        return [(p["body_jaxpr"], 1.0, True)]
    if name == "cond":
        branches = p.get("branches", ())
        if not branches:
            return []
        # widest branch — the reference counts the executed module; without
        # runtime predicates the upper bound is the honest static choice
        def total(br):
            return sum(_prim_flops(e) for e in br.jaxpr.eqns)
        widest = max(branches, key=total)
        return [(widest, 1.0, False)]
    out = []
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in p and p[key] is not None:
            out.append((p[key], 1.0, False))
    if "branches" in p and name != "cond":
        out.extend((b, 1.0, False) for b in p["branches"])
    return out


def per_module_flops(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """Trace ``fn`` and return {module_scope_path: flops} — scope paths come
    from the flax name stack; the empty path collects unscoped ops."""
    closed = jax.make_jaxpr(lambda *a, **k: fn(*a, **k))(*args, **kwargs)
    acc: Dict[str, float] = {}
    notes = {"dynamic_while": False}

    def scope_of(eqn, prefix: str) -> str:
        ns = str(eqn.source_info.name_stack)
        # transform frames show as e.g. 'transpose(jvp(...))' — strip
        # wrapper frames, keep the module path segments
        parts = [seg for seg in ns.split("/")
                 if seg and "(" not in seg and ")" not in seg]
        own = "/".join(parts)
        if not own:
            return prefix
        # inner-jaxpr name stacks restart at the lifting module (a scan
        # body's stack begins at 'blocks', not 'LlamaModel/blocks') — join
        # with the enclosing equation's scope unless already absolute
        if not prefix or own.startswith(prefix):
            return own
        return f"{prefix}/{own}"

    def walk(jaxpr, mult: float, prefix: str):
        for eqn in jaxpr.eqns:
            scope = scope_of(eqn, prefix)
            f = _prim_flops(eqn) * mult
            if f:
                acc[scope] = acc.get(scope, 0.0) + f
            for inner, m, est in _inner_jaxprs(eqn):
                if est:
                    notes["dynamic_while"] = True
                walk(inner.jaxpr, mult * m, scope)

    walk(closed.jaxpr, 1.0, "")
    if notes["dynamic_while"]:
        logger.info("per_module_flops: while_loop counted as ONE iteration "
                    "(dynamic trip count)")
    return acc


def _params_by_scope(params: Any, root: str) -> Dict[str, int]:
    """Param counts keyed by module scope path (prefixed with root)."""
    from deepspeed_tpu.parallel.partition import path_str

    out: Dict[str, int] = {}

    def visit(path, leaf):
        if not hasattr(leaf, "size"):
            return leaf
        parts = path_str(path).split("/")
        scope = "/".join([root] + parts[:-1]) if parts[:-1] else root
        out[scope] = out.get(scope, 0) + int(leaf.size)
        return leaf

    jax.tree_util.tree_map_with_path(visit, params)
    return out


class ModuleTree:
    """Aggregated per-module profile: every node's flops include its
    subtree, so parent rows are exact sums (+ own unattributed ops)."""

    def __init__(self, flops_by_scope: Dict[str, float],
                 params_by_scope: Optional[Dict[str, int]] = None):
        self.flops_by_scope = flops_by_scope
        self.params_by_scope = params_by_scope or {}
        self.total_flops = sum(flops_by_scope.values())
        self.total_params = sum(self.params_by_scope.values())

    def subtree_flops(self, scope: str) -> float:
        pre = scope + "/"
        return sum(f for s, f in self.flops_by_scope.items()
                   if s == scope or s.startswith(pre))

    def subtree_params(self, scope: str) -> int:
        pre = scope + "/"
        return sum(p for s, p in self.params_by_scope.items()
                   if s == scope or s.startswith(pre))

    def rows(self, depth: int = -1, top: int = 0) -> List[Tuple[str, float, int]]:
        """(scope, subtree_flops, subtree_params) rows ordered as a tree
        walk; ``depth`` limits nesting (-1 = all), ``top`` keeps only the
        top-k children per level by flops (0 = all)."""
        scopes = set()
        for s in list(self.flops_by_scope) + list(self.params_by_scope):
            parts = s.split("/") if s else []
            for i in range(1, len(parts) + 1):
                scopes.add("/".join(parts[:i]))

        children: Dict[str, set] = {}
        roots = set()
        for s in scopes:
            if "/" in s:
                parent = s.rsplit("/", 1)[0]
                children.setdefault(parent, set()).add(s)
            else:
                roots.add(s)

        out: List[Tuple[str, float, int]] = []

        def visit(scope, d):
            if depth >= 0 and d > depth:
                return
            out.append((scope, self.subtree_flops(scope),
                        self.subtree_params(scope)))
            kids = sorted(children.get(scope, ()),
                          key=lambda s: -self.subtree_flops(s))
            if top > 0:
                kids = kids[:top]
            for k in kids:
                visit(k, d + 1)

        for r in sorted(roots, key=lambda s: -self.subtree_flops(s)):
            visit(r, 0)
        return out

    def registry_rows(self, depth: int = 2, top: int = 3) -> Dict[str, float]:
        """Flat ``module.<scope>.flops/params`` dict of the top rows —
        the shape the dsttrain ``profiling`` registry section carries
        (bounded: ``depth``/``top`` keep a 32-layer model from turning
        the metrics snapshot into a per-op dump)."""
        out: Dict[str, float] = {}
        for scope, flops, nparams in self.rows(depth=depth, top=top):
            key = scope.replace("/", ".")
            out[f"module.{key}.flops"] = float(flops)
            if nparams:
                out[f"module.{key}.params"] = float(nparams)
        return out

    def format(self, depth: int = -1, top: int = 0) -> str:
        from deepspeed_tpu.profiling.flops_profiler import _fmt

        lines = ["depth  module                                    "
                 "flops            params"]
        for scope, flops, nparams in self.rows(depth, top):
            d = scope.count("/")
            name = ("  " * d) + (scope.rsplit("/", 1)[-1] or "<root>")
            pct = 100.0 * flops / self.total_flops if self.total_flops else 0
            lines.append(f"{d:<5d}  {name:<40s}  {_fmt(flops, 'FLOPs'):>12s} "
                         f"({pct:4.1f}%)  {_fmt(float(nparams)):>8s}")
        lines.append(f"total  {'':40s}  "
                     f"{_fmt(self.total_flops, 'FLOPs'):>12s} (100%)  "
                     f"{_fmt(float(self.total_params)):>8s}")
        return "\n".join(lines)


def profile_modules(fn: Callable, params: Any, *args,
                    root: Optional[str] = None, **kwargs) -> ModuleTree:
    """One-shot per-module profile of ``fn(params, *args)``.

    ``root``: module scope prefix for the params tree (auto-detected from
    the traced scopes' common root when omitted)."""
    flops = per_module_flops(fn, params, *args, **kwargs)
    if root is None:
        tops = {s.split("/")[0] for s in flops if s}
        root = tops.pop() if len(tops) == 1 else ""
    pscope = _params_by_scope(params, root) if root else \
        _params_by_scope(params, "")
    return ModuleTree(flops, pscope)
