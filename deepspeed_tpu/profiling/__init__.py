from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler,
    cost_analysis,
    count_params,
    profile_model,
)
