"""FLOPs profiler from jaxpr cost analysis.

TPU-native analogue of reference ``profiling/flops_profiler/profiler.py:23``
(``FlopsProfiler``): the reference monkey-patches torch functionals to count
MACs per module; here the compiler already knows — ``jax.jit(...).lower()``
+ ``compile().cost_analysis()`` yields exact FLOPs/bytes for the whole
program, and per-module numbers come from profiling submodule applies.

Also provides ``duration`` by timing the compiled step, and the same
human-readable summary surface (``print_model_profile``-style).
"""

import time
from typing import Any, Callable, Dict, Optional

import jax

from deepspeed_tpu.utils.logging import logger


def _fmt(n: Optional[float], unit: str = "") -> str:
    if n is None:
        return "n/a"
    for scale, suffix in [(1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")]:
        if abs(n) >= scale:
            return f"{n / scale:.2f} {suffix}{unit}"
    return f"{n:.2f} {unit}"


def cost_analysis(fn: Callable, *args, static_argnums=(), **kwargs) -> Dict[str, float]:
    """Compile ``fn`` and return {'flops':..., 'bytes accessed':...}."""
    lowered = jax.jit(fn, static_argnums=static_argnums).lower(*args, **kwargs)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def count_params(params: Any) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params)
               if hasattr(x, "size"))


class FlopsProfiler:
    """Profile a jitted step: total FLOPs, params, achieved FLOPS and
    latency. ``start_profile``/``stop_profile``/``print_model_profile``
    mirror the reference's API shape."""

    def __init__(self, fn: Optional[Callable] = None, params: Optional[Any] = None):
        self.fn = fn
        self.params = params
        self.flops = 0.0
        self.macs = 0.0
        self.bytes_accessed = 0.0
        self.duration = 0.0
        self.module_tree = None
        self._started = False

    def profile_modules(self, fn: Callable, params: Any, *args, **kwargs):
        """Per-module flops tree (reference profiler.py:23 per-module
        report): jaxpr traversal attributing each op to its flax scope —
        see profiling/module_profiler.py. Stored for print_model_profile's
        detailed view; returns the ModuleTree."""
        from deepspeed_tpu.profiling.module_profiler import profile_modules

        self.module_tree = profile_modules(fn, params, *args, **kwargs)
        return self.module_tree

    def start_profile(self) -> None:
        self._started = True

    def profile(self, fn: Callable, *args, time_it: bool = True,
                warmup: int = 1, iters: int = 3, **kwargs) -> Dict[str, float]:
        ca = cost_analysis(fn, *args, **kwargs)
        self.flops = float(ca.get("flops", 0.0))
        self.macs = self.flops / 2.0
        self.bytes_accessed = float(ca.get("bytes accessed", 0.0))
        if time_it:
            jitted = jax.jit(fn)
            for _ in range(warmup):
                jax.block_until_ready(jitted(*args, **kwargs))
            t0 = time.time()
            out = None
            for _ in range(iters):
                out = jitted(*args, **kwargs)
            jax.block_until_ready(out)
            self.duration = (time.time() - t0) / iters
        return {
            "flops": self.flops,
            "macs": self.macs,
            "bytes_accessed": self.bytes_accessed,
            "duration": self.duration,
            "flops_per_sec": self.flops / self.duration if self.duration else 0.0,
        }

    def stop_profile(self) -> None:
        self._started = False

    def registry_section(self, module_depth: int = 2,
                         top_modules: int = 3) -> Dict[str, float]:
        """Flat numeric dict for the dsttrain ``profiling`` registry
        pull section (docs/OBSERVABILITY.md): whole-program cost
        analysis plus the top per-module rows when ``profile_modules``
        ran — so the monitor sinks, ``dst prof --train`` and the
        Prometheus exporter drain the profiler's output instead of it
        living only in its own log lines."""
        out: Dict[str, float] = {
            "flops": self.flops,
            "macs": self.macs,
            "bytes_accessed": self.bytes_accessed,
        }
        if self.duration:
            out["duration_s"] = self.duration
            out["flops_per_sec"] = self.flops / self.duration
        n_params = getattr(self, "n_params", None)
        if n_params is None and self.params is not None:
            n_params = count_params(self.params)
        if n_params:
            out["params"] = float(n_params)
        if self.module_tree is not None:
            out.update(self.module_tree.registry_rows(
                depth=module_depth, top=top_modules))
        return out

    def get_total_flops(self, as_string: bool = False):
        return _fmt(self.flops, "FLOPs") if as_string else self.flops

    def get_total_macs(self, as_string: bool = False):
        return _fmt(self.macs, "MACs") if as_string else self.macs

    def get_total_duration(self, as_string: bool = False):
        return f"{self.duration * 1e3:.2f} ms" if as_string else self.duration

    def print_model_profile(self, params: Optional[Any] = None,
                            detailed: bool = True, module_depth: int = -1,
                            top_modules: int = 0) -> str:
        """Summary + (``detailed``) the per-module tree with the reference's
        depth/top-k controls (profile.module_depth / top_modules)."""
        lines = ["", "-------------------------- Flops Profiler --------------------------"]
        if params is not None:
            lines.append(f"params:              {_fmt(count_params(params))}")
        lines.append(f"fwd(+bwd) flops:     {_fmt(self.flops, 'FLOPs')}")
        lines.append(f"fwd(+bwd) MACs:      {_fmt(self.macs, 'MACs')}")
        lines.append(f"bytes accessed:      {_fmt(self.bytes_accessed, 'B')}")
        if self.duration:
            lines.append(f"latency:             {self.duration * 1e3:.2f} ms")
            lines.append(f"achieved:            {_fmt(self.flops / self.duration, 'FLOPS')}")
        if detailed and self.module_tree is not None:
            lines.append("-------------------- per-module (traced, pre-fusion) ----------------")
            lines.append(self.module_tree.format(depth=module_depth,
                                                 top=top_modules))
        lines.append("---------------------------------------------------------------------")
        report = "\n".join(lines)
        logger.info(report)
        return report


def profile_model(model, params, *args, **kwargs) -> Dict[str, float]:
    """One-shot: profile ``model.apply`` on the given inputs."""
    prof = FlopsProfiler()
    return prof.profile(lambda p, *a: model.apply({"params": p}, *a),
                        params, *args, **kwargs)
